"""Inference benchmark: KV-cache decode throughput on the flagship model.

Prints one JSON line per batch size: prefill tokens/s and steady-state
decode tokens/s/chip for the 0.8B Llama config (the serving-side
counterpart of bench.py's training MFU; decode is memory-bandwidth-bound,
so tokens/s scales with batch until HBM saturates), then the serving
probes: continuous batching vs the static path, the engine's stepwise
breakdown (dispatch/fetch/host per step + compile/upload counts), and
the engine-vs-raw decode throughput ratio. Writes BENCH_INFER.json; a
CPU fallback run uses the tiny config and merges its "(cpu fallback)"
entries into the artifact without touching committed TPU entries.

Run: python bench_infer.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import replace


def _ensure_backend():
    """A dead TPU tunnel hangs jax.devices() forever; probe it in a
    killable subprocess (bench.py's pattern) and fall back to CPU."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return
    from bench import _probe_tunnel

    if not _probe_tunnel():
        print("[bench_infer] TPU tunnel dead; falling back to CPU",
              file=sys.stderr, flush=True)
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""


_ensure_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _engine_stepwise_probe(params, cfg, on_tpu):
    """Decompose the continuous-batching engine's steady-state step and
    compare it with a raw jitted batch=num_slots decode at the same
    shapes (same cache length, same batch rows).

    Two entries: (1) the per-step breakdown — raw floor, engine step,
    overhead, and where the overhead goes (dispatch / fetch / host),
    plus compile and sampling-param-upload counts inside the window
    (both must be 0: the r5 engine paid per-step host<->device traffic
    over the TPU tunnel — an implied 78.9 ms engine step against the
    artifact's 6.93 ms raw batch-8 decode, i.e. ~72 ms/step of pure
    sync overhead); (2) the engine-vs-raw throughput ratio for an
    all-greedy full-occupancy run.

    Measured on this box (CPU, tiny config, BENCH_INFER.json): engine
    step 0.957 ms vs raw floor 1.044 ms — overhead -0.087 ms, i.e.
    zero within this box's run-to-run noise — with 0 compiles and 0
    param uploads in the window, and an engine-vs-raw throughput
    ratio of 0.935. The r5 ~72 ms/step overhead is gone because its
    causes are gone, not faster: sampling params live on device and
    re-upload only on admission/eviction, the token fetch is
    double-buffered (copy_to_host_async overlaps the next dispatch),
    and the step programs never retrace after warmup.

    Residual gap, by construction: the engine's decode step stays
    intrinsically heavier than a raw argmax decode — masked per-slot
    cache writes at per-slot offsets, the on-device pick with
    per-slot temperature/top-k/top-p gathers, and per-step host
    bookkeeping (slot table, handle queues, timing) that no amount of
    device residency removes. On CPU that difference is smaller than
    measurement noise (hence the ~0 overhead above); on TPU it is
    bounded by compute, no longer multiplied by tunnel RTT.
    """
    from ray_tpu.models.generate import decode_step, init_kv_cache, prefill
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    num_slots = 4
    plen = 8
    n_tok = 256 if on_tpu else 192
    max_len = plen + n_tok + 8
    raw_steps = 60
    window = 48
    rounds = 3  # min-of-N: this box's wall clock is noisy (factor ~2)

    # Raw floor: jitted decode at batch=num_slots over a cache of the
    # engine's [num_slots, max_len] shape, greedy argmax picks. The
    # cache is donated (as the engine's decode jit donates its k/v
    # buffers) so the floor measures in-place appends, not a
    # copy-the-cache-per-step strawman.
    jprefill = jax.jit(lambda p, t, c: prefill(p, t, c, cfg))

    def _raw_step(p, t, c):  # decode + greedy pick in ONE program,
        logits, c = decode_step(p, t, c, cfg)  # like the engine's step
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), c

    jdecode = jax.jit(_raw_step, donate_argnums=(2,))
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (num_slots, plen), 0, cfg.vocab_size
    )
    logits, c = jprefill(params, prompt,
                         init_kv_cache(cfg, num_slots, max_len))
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tok, c = jdecode(params, tok, c)  # warm (donates + replaces c)
    jax.device_get(tok)
    raw_step_ms = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(raw_steps):
            tok, c = jdecode(params, tok, c)
        jax.device_get(tok)  # rtlint: disable=RT001 — stepwise probe: the per-step sync IS the measured quantity
        raw_s = time.perf_counter() - t0
        raw_step_ms = min(raw_step_ms, raw_s / raw_steps * 1e3)
    raw_tps = num_slots / raw_step_ms * 1e3

    prompts = [
        list(map(int, jax.device_get(jax.random.randint(
            jax.random.fold_in(jax.random.PRNGKey(3), i), (plen,),
            0, cfg.vocab_size
        ))))
        for i in range(num_slots)
    ]
    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=num_slots, max_len=max_len,
        prefill_chunk=plen,
    )
    try:
        t_submit = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=n_tok) for p in prompts]
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:  # full occupancy
            s0 = eng.stats()
            if s0["active"] == num_slots and s0["prefilling"] == 0:
                break
            time.sleep(0.002)
        settle = s0["steps"] + 2  # let the last admission's upload land
        while time.monotonic() < deadline:
            s0 = eng.stats()
            if s0["steps"] >= settle:
                break
            time.sleep(0.002)
        t0 = time.perf_counter()
        windows = []
        for _ in range(rounds):
            target = s0["steps"] + window
            s1 = s0
            while time.monotonic() < deadline:
                s1 = eng.stats()
                if s1["steps"] >= target:
                    break
                time.sleep(0.002)
            t1 = time.perf_counter()
            windows.append((s0, s1, t0, t1))
            s0, t0 = s1, t1
        for h in handles:
            h.result(timeout=600)
        t_done = time.perf_counter()
    finally:
        eng.shutdown()

    # Best window = the least-preempted one (same min-of-N as raw).
    s0, s1, t0, t1 = min(
        windows,
        key=lambda x: (x[3] - x[2]) / max(x[1]["steps"] - x[0]["steps"], 1),
    )
    w = max(s1["steps"] - s0["steps"], 1)
    wt = max(s1["timing"]["steps_timed"] - s0["timing"]["steps_timed"], 1)
    engine_step_ms = (t1 - t0) / w * 1e3

    def part(name):
        key = f"{name}_ms_total"
        return round((s1["timing"][key] - s0["timing"][key]) / wt, 3)

    suffix = "" if on_tpu else " (cpu fallback)"
    breakdown = {
        "metric": "engine step breakdown" + suffix,
        "num_slots": num_slots,
        "window_steps": w,
        "raw_decode_step_ms": round(raw_step_ms, 3),
        "engine_step_ms": round(engine_step_ms, 3),
        "engine_overhead_ms": round(engine_step_ms - raw_step_ms, 3),
        "dispatch_ms": part("dispatch"),
        "fetch_ms": part("fetch"),
        "host_ms": part("host"),
        "compiles_in_window": s1["compiles"] - s0["compiles"],
        "param_uploads_in_window": (
            s1["param_uploads"] - s0["param_uploads"]
        ),
    }
    engine_tps = num_slots * n_tok / (t_done - t_submit)
    ratio = {
        "metric": "engine vs raw decode throughput" + suffix,
        "num_slots": num_slots,
        "tokens_per_request": n_tok,
        "raw_decode_tokens_per_s": round(raw_tps, 1),
        "engine_tokens_per_s": round(engine_tps, 1),
        "engine_vs_raw_throughput_ratio": round(engine_tps / raw_tps, 3),
    }
    return [breakdown, ratio]


def main():
    from ray_tpu.models import configs, init_params
    from ray_tpu.models.generate import decode_step, init_kv_cache, prefill

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = replace(configs.get_config("llama2-1b"), n_layers=12,
                      max_seq=1024, remat=False)
        batches = (1, 8, 32)
        prompt_len, decode_steps = 512, 64
    else:
        cfg = replace(configs.tiny, remat=False)
        batches = (4,)
        prompt_len, decode_steps = 32, 8

    params = init_params(jax.random.PRNGKey(0), cfg)
    results = []
    for batch in batches:
        max_len = prompt_len + decode_steps
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size
        )
        cache = init_kv_cache(cfg, batch, max_len)
        jprefill = jax.jit(lambda p, t, c: prefill(p, t, c, cfg))  # rtlint: disable=RT002 — per-config rebuild is intended; each config needs its own wrapper
        jdecode = jax.jit(lambda p, t, c: decode_step(p, t, c, cfg))  # rtlint: disable=RT002 — per-config rebuild is intended

        # Warm both compilations.
        logits, cache1 = jprefill(params, prompt, cache)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        _, cache2 = jdecode(params, tok, cache1)
        jax.device_get(logits)  # rtlint: disable=RT001 — timed section deliberately syncs to measure true step latency

        t0 = time.perf_counter()
        logits, cache1 = jprefill(params, prompt, init_kv_cache(cfg, batch, max_len))
        jax.device_get(logits)  # rtlint: disable=RT001 — timed section deliberately syncs
        prefill_s = time.perf_counter() - t0

        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        t0 = time.perf_counter()
        c = cache1
        for _ in range(decode_steps):
            logits, c = jdecode(params, tok, c)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        jax.device_get(tok)  # rtlint: disable=RT001 — timed section deliberately syncs
        decode_s = time.perf_counter() - t0

        entry = {
            "metric": "llama2(0.8B) decode tokens/s/chip" if on_tpu
                      else "tiny decode tokens/s (cpu fallback)",
            "batch": batch,
            "prefill_tokens_per_s": round(batch * prompt_len / prefill_s, 1),
            "decode_tokens_per_s": round(batch * decode_steps / decode_s, 1),
            "ms_per_decode_step": round(decode_s / decode_steps * 1e3, 2),
        }
        print(json.dumps(entry), flush=True)
        results.append(entry)

    # Continuous batching at mixed arrivals vs static batch=1 (the
    # serving north-star, BASELINE.json configs[4]): requests join a
    # running decode loop at step boundaries instead of waiting for the
    # current batch to finish.
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    n_req = 8 if on_tpu else 6
    n_tok = 32 if on_tpu else 8
    cb_prompt_len = min(prompt_len, 64)
    rng = jax.random.PRNGKey(7)
    prompts = [
        list(map(int, jax.device_get(jax.random.randint(
            jax.random.fold_in(rng, i), (cb_prompt_len,), 0, cfg.vocab_size
        ))))
        for i in range(n_req)
    ]
    from ray_tpu.models.generate import generate

    # Warm the static path's compilation before timing it (the engine's
    # warmup request below plays the same role for the continuous path).
    jax.device_get(generate(
        params, jnp.asarray([prompts[0]], dtype=jnp.int32), cfg,
        max_new_tokens=n_tok,
    ))
    t0 = time.perf_counter()
    for p in prompts:
        jax.device_get(generate(  # rtlint: disable=RT001 — end-to-end timing requires draining the whole generation
            params, jnp.asarray([p], dtype=jnp.int32), cfg,
            max_new_tokens=n_tok,
        ))
    static_s = time.perf_counter() - t0

    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=4, max_len=cb_prompt_len + n_tok + 1,
        prefill_chunk=cb_prompt_len,
    )
    try:
        eng.submit(prompts[0], max_new_tokens=n_tok).result(timeout=600)
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=n_tok) for p in prompts]
        for h in handles:
            h.result(timeout=600)
        cont_s = time.perf_counter() - t0
    finally:
        eng.shutdown()
    entry = {
        "metric": "continuous batching tokens/s" + (
            "/chip" if on_tpu else " (cpu fallback)"
        ),
        "requests": n_req,
        "tokens_per_request": n_tok,
        "static_batch1_tokens_per_s": round(n_req * n_tok / static_s, 1),
        "continuous_tokens_per_s": round(n_req * n_tok / cont_s, 1),
        "speedup_vs_static": round(static_s / cont_s, 2),
    }
    print(json.dumps(entry), flush=True)
    results.append(entry)

    for entry in _engine_stepwise_probe(params, cfg, on_tpu):
        print(json.dumps(entry), flush=True)
        results.append(entry)

    if on_tpu:
        with open("BENCH_INFER.json", "w") as f:
            json.dump(results, f, indent=1)
    else:
        # CPU fallback entries are labeled "(cpu fallback)": merge them
        # into the artifact WITHOUT touching committed TPU entries, so
        # the stepwise breakdown is pinned even on a CPU-only box.
        try:
            with open("BENCH_INFER.json") as f:
                existing = json.load(f)
        except (OSError, json.JSONDecodeError):
            existing = []
        ours = {e["metric"] for e in results}
        merged = [e for e in existing if e["metric"] not in ours]
        merged += results
        with open("BENCH_INFER.json", "w") as f:
            json.dump(merged, f, indent=1)
        print("[bench_infer] cpu fallback: merged cpu-labeled entries "
              "into BENCH_INFER.json (TPU entries preserved)",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
