"""Inference benchmark: KV-cache decode throughput on the flagship model.

Prints one JSON line per batch size: prefill tokens/s and steady-state
decode tokens/s/chip for the 0.8B Llama config (the serving-side
counterpart of bench.py's training MFU; decode is memory-bandwidth-bound,
so tokens/s scales with batch until HBM saturates). Writes
BENCH_INFER.json. CPU fallback uses the tiny config.

Run: python bench_infer.py
"""

from __future__ import annotations

import json
import time
from dataclasses import replace

import jax
import jax.numpy as jnp


def main():
    from ray_tpu.models import configs, init_params
    from ray_tpu.models.generate import decode_step, init_kv_cache, prefill

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = replace(configs.get_config("llama2-1b"), n_layers=12,
                      max_seq=1024, remat=False)
        batches = (1, 8, 32)
        prompt_len, decode_steps = 512, 64
    else:
        cfg = replace(configs.tiny, remat=False)
        batches = (4,)
        prompt_len, decode_steps = 32, 8

    params = init_params(jax.random.PRNGKey(0), cfg)
    results = []
    for batch in batches:
        max_len = prompt_len + decode_steps
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size
        )
        cache = init_kv_cache(cfg, batch, max_len)
        jprefill = jax.jit(lambda p, t, c: prefill(p, t, c, cfg))
        jdecode = jax.jit(lambda p, t, c: decode_step(p, t, c, cfg))

        # Warm both compilations.
        logits, cache1 = jprefill(params, prompt, cache)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        _, cache2 = jdecode(params, tok, cache1)
        jax.device_get(logits)

        t0 = time.perf_counter()
        logits, cache1 = jprefill(params, prompt, init_kv_cache(cfg, batch, max_len))
        jax.device_get(logits)
        prefill_s = time.perf_counter() - t0

        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        t0 = time.perf_counter()
        c = cache1
        for _ in range(decode_steps):
            logits, c = jdecode(params, tok, c)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        jax.device_get(tok)
        decode_s = time.perf_counter() - t0

        entry = {
            "metric": "llama2(0.8B) decode tokens/s/chip" if on_tpu
                      else "tiny decode tokens/s (cpu fallback)",
            "batch": batch,
            "prefill_tokens_per_s": round(batch * prompt_len / prefill_s, 1),
            "decode_tokens_per_s": round(batch * decode_steps / decode_s, 1),
            "ms_per_decode_step": round(decode_s / decode_steps * 1e3, 2),
        }
        print(json.dumps(entry), flush=True)
        results.append(entry)

    with open("BENCH_INFER.json", "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
