"""Scalability envelope microbenchmarks.

Analog of the reference's release/benchmarks scalability envelope
(release/benchmarks/README.md: many queued tasks, many actors, many
object args, many objects per get, object broadcast across nodes) scaled
to a single CI host. Writes BENCH_SCALE.json and prints one JSON line
per probe.

Run: python bench_scale.py [--quick]

## Cost curves (round 5, this 1-core host)

Per-op cost vs envelope size (committed under the "cost_curves" entry in
BENCH_SCALE.json — quote numbers from the artifact, not from here; the
suite's test_doc_claims_match_artifacts pins the doc copies):
  * queued tasks 10k->1M: ~82-129 us/task — flat to the reference's
    single-node envelope (per-class dispatch queues + batched direct
    transport keep per-op cost O(1) in queue depth).
  * live actors: flat ~19-24 ms/actor create+call while the HOST can
    back fresh pages quickly, then a knee (r5 artifact: ~54 ms at
    n=1000, ~61 at n=2000 — the 1k->2k segment grows only ~14% for 2x
    scale, so the post-knee curve is flat-ish; the knee itself is the
    regime change). Analysis (see "memory_backing" probe): each worker
    process costs ~5 MB private memory, and this VM's host backs only
    the first few GB of fresh guest pages quickly — beyond that,
    first-touch page faults slow 8-25x system-wide, which is exactly
    where every >=800-actor run knees. The per-actor cost the FRAMEWORK
    controls (GCS registration, scheduling, zygote fork, boot protocol)
    stays flat: the knee tracks cumulative fresh memory, not actor
    count (it moves with prior host memory pressure and does not
    reproduce after freed memory is reused). Mitigations shipped:
    zygote generations (re-exec every zygote_respawn_after forks; Linux
    anon_vma chains otherwise grow with COW-faulted siblings) and a
    pre-fork gc.freeze. The n=2000 point is committed for honesty; on
    this host the post-knee points measure paging, not bookkeeping.
  * placement groups 10->100: ~0.4-0.5 ms/PG — flat (2-phase commit cost
    independent of PG count).
  * broadcast: 256MB->4 nodes 0.28s steady-state (3.6 GB/s), ->8 nodes
    0.44s (4.5 GB/s); the committed cold_wall_s shows the first-pass
    fresh-page cost separately.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import ray_tpu as rt


def probe(name, fn, results):
    t0 = time.perf_counter()
    extra = fn() or {}
    dt = time.perf_counter() - t0
    entry = {"probe": name, "wall_s": round(dt, 2), **extra}
    print(json.dumps(entry), flush=True)
    results.append(entry)


def main():
    quick = "--quick" in sys.argv
    results = []
    rt.init(num_cpus=4, object_store_memory=1 << 30)

    @rt.remote
    def noop(x=None):
        return 0

    @rt.remote
    class A:
        def ping(self):
            return 0

    # Warm the worker pool.
    rt.get([noop.remote() for _ in range(8)])

    # 1. Tasks queued on one node at once (reference envelope: 1M on a
    # 64-core box; scaled to the 1-core CI host).
    n_tasks = 2_000 if quick else 10_000
    probe(
        f"{n_tasks} queued tasks drain",
        lambda: (
            rt.get([noop.remote() for _ in range(n_tasks)], timeout=1200),
            {"tasks": n_tasks},
        )[1],
        results,
    )

    # 2. Many live actors (reference envelope: 40k cluster-wide).
    n_actors = 50 if quick else 200
    def many_actors():
        actors = [A.options(num_cpus=0.001).remote() for _ in range(n_actors)]
        rt.get([a.ping.remote() for a in actors], timeout=1200)
        for a in actors:
            rt.kill(a)
        return {"actors": n_actors}
    probe(f"{n_actors} actors created+called", many_actors, results)

    # 3. Many objects in one rt.get (reference envelope: 10k plasma
    # objects per get).
    n_objs = 2_000 if quick else 10_000
    def many_objects():
        refs = [rt.put(i) for i in range(n_objs)]
        out = rt.get(refs, timeout=1200)
        assert out[-1] == n_objs - 1
        return {"objects": n_objs}
    probe(f"{n_objs} objects in one get", many_objects, results)

    # 4. Many object args to a single task (reference envelope: 10k args).
    n_args = 500 if quick else 2_000
    @rt.remote
    def count_args(*args):
        return len(args)
    def many_args():
        refs = [rt.put(i) for i in range(n_args)]
        assert rt.get(count_args.remote(*refs), timeout=1200) == n_args
        return {"args": n_args}
    probe(f"{n_args} object args to one task", many_args, results)

    # 5. Large-object broadcast to every worker (reference envelope: 1GiB
    # broadcast to 50 nodes; here: 64MB to the worker pool).
    blob = np.zeros(64 * 1024 * 1024 // 8)
    @rt.remote
    def touch(x):
        return x.nbytes
    def broadcast():
        ref = rt.put(blob)
        sizes = rt.get([touch.remote(ref) for _ in range(8)], timeout=1200)
        assert all(s == blob.nbytes for s in sizes)
        return {"mb": blob.nbytes >> 20, "consumers": 8}
    probe("64MB broadcast to 8 tasks", broadcast, results)

    # 6. Control-plane profiler (ISSUE 6): lifecycle phase decomposition
    # at two scale points, GCS RPC cost of an actor create, and the
    # sampling-off fast-path overhead gate.
    from ray_tpu.util import lifecycle, profiling
    from ray_tpu.util.state.api import StateApiClient

    def lifecycle_decomposition():
        """Serial round-trips at two scale points with sampling on: the
        stitched per-phase breakdown must explain >= ~90% of the
        measured us a task spends submit->complete (burst submissions
        complete batch-granular, so the contract is per round-trip).
        loop_us_per_task additionally counts the driver's own get-return
        wakeup + loop bookkeeping, which no task's lifecycle contains."""
        points = []
        seen: set = set()
        for n in ((30, 100) if quick else (200, 1000)):
            lifecycle.set_sample_rate(1.0)
            t0 = time.perf_counter()
            for i in range(n):
                rt.get(noop.remote(), timeout=600)
            wall = time.perf_counter() - t0
            lifecycle.set_sample_rate(0.0)
            profiling.flush()
            time.sleep(2.5)  # worker task-event flush interval + slack
            c = StateApiClient()
            try:
                events = [e for e in c.task_events(warn=False)
                          if e.get("type") == "LIFECYCLE_SPAN"]
            finally:
                c.close()
            recs = {
                k: r for k, r in lifecycle.stitch(events).items()
                if k not in seen and r["e2e_s"] and "worker" in r["hops"]
            }
            seen.update(lifecycle.stitch(events))
            measured_us = 1e6 * wall / n
            sums = [
                1e6 * sum(d for p, d in r["phases"].items()
                          if p in lifecycle.SUM_PHASES)
                for r in recs.values()
            ]
            agg = lifecycle.aggregate(recs)
            phases_us = {
                p: round(agg[p]["mean_us"], 1)
                for p in lifecycle.PHASE_ORDER if p in agg
            }
            mean_sum = sum(sums) / len(sums) if sums else 0.0
            e2es = [1e6 * r["e2e_s"] for r in recs.values()]
            mean_e2e = sum(e2es) / len(e2es) if e2es else 0.0
            points.append({
                "n": n,
                "sampled": len(recs),
                "us_per_task": round(mean_e2e, 1),
                "loop_us_per_task": round(measured_us, 1),
                "phases_us": phases_us,
                "phase_sum_us": round(mean_sum, 1),
                "phase_sum_fraction_of_e2e": round(
                    mean_sum / mean_e2e, 3) if mean_e2e else 0.0,
            })
            print(json.dumps({"probe": f"lifecycle decomposition n={n}",
                              **points[-1]}), flush=True)
        return {"points": points}

    probe("lifecycle phase decomposition", lifecycle_decomposition, results)

    def rpc_per_actor_create():
        """Total GCS RPCs (all methods, both directions land on the
        server counter) the cluster spends per actor create+first-call."""
        k = 10 if quick else 20
        c = StateApiClient()
        try:
            before = dict(c.call("gcs_stats").get("rpc_counts") or {})
            actors = [A.options(num_cpus=0.001).remote() for _ in range(k)]
            rt.get([a.ping.remote() for a in actors], timeout=600)
            after = dict(c.call("gcs_stats").get("rpc_counts") or {})
        finally:
            c.close()
        for a in actors:
            rt.kill(a)
        delta = {
            m: after.get(m, 0) - before.get(m, 0)
            for m in after if after.get(m, 0) > before.get(m, 0)
        }
        top = dict(sorted(delta.items(), key=lambda kv: -kv[1])[:8])
        return {
            "actors": k,
            "gcs_rpcs_per_actor_create": round(
                sum(delta.values()) / k, 2),
            "top_methods": top,
        }

    probe("gcs rpcs per actor create", rpc_per_actor_create, results)

    def off_path_overhead():
        """Sampling-off cost gate (< 2 us/task). Two parts: a guard
        micro-bench of the EXACT rate-0 ops a task pays (one module-attr
        check at submit, spec.get misses at the hops), and a paired
        off/off noise floor showing the end-to-end per-task cost is
        indistinguishable from run-to-run noise."""
        spec = {"task_id": b"x" * 16, "name": "noop"}
        n_ops = 200_000
        t0 = time.perf_counter()
        for _ in range(n_ops):
            if lifecycle.enabled and lifecycle.sample():
                pass
            spec.get("sampled")
            spec.get("sampled")
            spec.get("sampled")
            spec.get("_lc_queue_wait")
        ops_us = 1e6 * (time.perf_counter() - t0) / n_ops

        def burst(n=200):
            t0 = time.perf_counter()
            rt.get([noop.remote() for _ in range(n)], timeout=600)
            return 1e6 * (time.perf_counter() - t0) / n

        arm_a, arm_b = [], []
        for _ in range(3 if quick else 5):
            arm_a.append(burst())
            arm_b.append(burst())

        def med(xs):
            return sorted(xs)[len(xs) // 2]

        return {
            "fastpath_ops_us_per_task": round(ops_us, 3),
            "paired_noise_us_per_task": round(abs(med(arm_a) - med(arm_b)), 2),
            "gate_us": 2.0,
        }

    probe("lifecycle off-path overhead", off_path_overhead, results)

    # 7. Cost curves (VERDICT r3 item 8): per-op cost must stay flat as
    # the envelope grows — the per-class dispatch queues and batched
    # transports are supposed to make cost O(1) per op, not O(queued).
    # Reference envelope: 1M queued tasks / 40k actors / 2k nodes
    # (release/benchmarks/README.md); scaled to this 1-core host.
    if not quick:
        curve: dict = {"tasks": [], "actors": [], "placement_groups": []}

        # 0. Host memory-backing context: first-touch cost of fresh
        # anonymous pages, sampled before the envelope probes. On thinly
        # backed VMs this rate collapses once cumulative fresh memory
        # passes the host's fast pool — the regime change that bends the
        # actor curve below (every worker process is ~5MB of fresh
        # pages). Committed so the artifact carries its own context.
        mb_points = []
        for _ in range(3):
            t0 = time.perf_counter()
            b = bytearray(512 << 20)
            for off in range(0, len(b), 4096):
                b[off] = 1
            mb_points.append(round(time.perf_counter() - t0, 2))
            del b
        curve["memory_backing"] = {"touch_512mb_s": mb_points}
        print(json.dumps({"probe": "memory_backing",
                          **curve["memory_backing"]}), flush=True)

        # The final point IS the reference's headline single-node envelope
        # (1,000,000 queued tasks, release/benchmarks/README.md:30) — run
        # here on 1 core vs the reference's 64-core measurement box.
        for n in (10_000, 30_000, 100_000, 300_000, 1_000_000):
            t0 = time.perf_counter()
            rt.get([noop.remote() for _ in range(n)], timeout=3600)
            dt = time.perf_counter() - t0
            curve["tasks"].append(
                {"n": n, "wall_s": round(dt, 2),
                 "us_per_task": round(1e6 * dt / n, 1)}
            )
            print(json.dumps({"probe": f"curve tasks n={n}",
                              **curve["tasks"][-1]}), flush=True)

        from ray_tpu.util import placement_group, remove_placement_group

        # PG curve BEFORE the actor curve: probes run light -> heavy so
        # thousands of dying actor workers never sit between a probe and
        # its deadline.
        for n in (10, 30, 100):
            t0 = time.perf_counter()
            pgs = [
                placement_group([{"CPU": 0.001}], strategy="PACK")
                for _ in range(n)
            ]
            for pg in pgs:
                assert pg.ready(timeout=600)
            t_up = time.perf_counter() - t0
            for pg in pgs:
                remove_placement_group(pg)
            dt = time.perf_counter() - t0
            curve["placement_groups"].append(
                {"n": n, "wall_s": round(dt, 2),
                 "ms_per_pg": round(1e3 * dt / n, 2)}
            )
            print(json.dumps({"probe": f"curve placement_groups n={n}",
                              **curve["placement_groups"][-1]}), flush=True)

        for n in (100, 300, 1000, 2000):
            t0 = time.perf_counter()
            actors = [A.options(num_cpus=0.0001).remote() for _ in range(n)]
            rt.get([a.ping.remote() for a in actors], timeout=3600)
            t_up = time.perf_counter() - t0
            for a in actors:
                rt.kill(a)
            dt = time.perf_counter() - t0
            curve["actors"].append(
                {"n": n, "wall_s": round(dt, 2),
                 "create_call_ms_per_actor": round(1e3 * t_up / n, 2),
                 "ms_per_actor": round(1e3 * dt / n, 2)}
            )
            print(json.dumps({"probe": f"curve actors n={n}",
                              **curve["actors"][-1]}), flush=True)
            # Settle: let the killed rung's workers die and their
            # resources return before the next rung times anything.
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                try:
                    if rt.get(noop.remote(), timeout=30) == 0:
                        time.sleep(1.0)
                        break
                except Exception:  # noqa: BLE001 — still churning
                    time.sleep(1.0)

        results.append({"probe": "cost_curves", **curve})

    rt.shutdown()

    # 6. Cross-NODE broadcast (reference envelope: 1GiB to 50+ nodes,
    # release/benchmarks/README.md:17; scaled to in-process raylets on
    # this CI host). Chunked pulls ride the pull byte budget + push
    # chunk caps (raylet flow control).
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    peer_counts = (2,) if quick else (4, 8)
    mb = 64 if quick else 256
    cluster = Cluster()
    cluster.add_node(num_cpus=1, object_store_memory=1 << 30)
    for _ in range(max(peer_counts)):
        cluster.add_node(num_cpus=1, object_store_memory=1 << 30)
    cluster.connect()
    try:
        @rt.remote
        def touch2(x):
            return x.nbytes if x is not None else 0

        # Warm one worker per peer node so the probes time the TRANSFER,
        # not first-task worker spawns.
        rt.get(
            [
                touch2.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=r.node_id.binary()
                    )
                ).remote(None)
                for r in cluster.raylets[1:]
            ],
            timeout=300,
        )

        def bcast_once(peers, tag):
            """One broadcast of a FRESH object to `peers` nodes."""
            blob2 = np.full(mb * 1024 * 1024 // 8, hash(tag) % 97, float)
            ref2 = rt.put(blob2)
            t0 = time.perf_counter()
            outs = rt.get(
                [
                    touch2.options(
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            node_id=r.node_id.binary()
                        )
                    ).remote(ref2)
                    for r in peers
                ],
                timeout=1200,
            )
            assert all(o == blob2.nbytes for o in outs)
            return time.perf_counter() - t0

        for n_peers in peer_counts:
            peers = cluster.raylets[1:1 + n_peers]
            # One untimed pass first: a fresh 256MB object x (n+1)
            # copies is > 1GB of first-touch pages, and on thinly
            # backed hosts (see memory_backing probe) cold-page faults
            # dominate the first transfer. Steady-state is the number
            # that reflects the transfer path itself; both are
            # committed.
            cold = bcast_once(peers, f"cold{n_peers}")
            dt = bcast_once(peers, f"warm{n_peers}")
            entry = {
                "probe": f"{mb}MB broadcast to {n_peers} nodes",
                "wall_s": round(dt, 2),
                "cold_wall_s": round(cold, 2),
                "mb": mb, "nodes": n_peers,
                "gb_moved": round(mb * n_peers / 1024, 2),
                "gb_per_s": round(mb * n_peers / 1024 / dt, 2),
            }
            print(json.dumps(entry), flush=True)
            results.append(entry)
    finally:
        cluster.shutdown()
    if not quick:
        # Only full runs overwrite the committed artifact.
        with open("BENCH_SCALE.json", "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
