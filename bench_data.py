"""Data->device feed benchmarks — the input-pipeline counterpart of
bench_core's microbenchmarks. Writes BENCH_DATA.json.

Three probes on a two-node in-process cluster (driver on the head node,
blocks produced on the second node so every consume is a real cross-node
pull), with chaos-injected per-pull transfer delay so the feed runs in
the fetch-latency-bound regime the paper cares about — deterministic,
network-free:

  1. feed throughput, serial vs pipelined: iterate batches under a
     synthetic 5ms training step. Serial (prefetch 0/0) pays
     pull + assemble + step per batch; pipelined (prefetch_blocks=4,
     prefetch_batches=4) overlaps concurrent pulls and background
     assembly with the step, collapsing to ~max(step, amortized pull).
  2. multi-ref get, old-vs-new: N remote refs fetched one blocking
     get at a time (the pre-refactor CoreClient.get shape) vs one
     batched rt.get(refs) that probes all N concurrently — the injected
     delay makes O(N) vs O(1) probe rounds directly visible.
  3. overlap ratio: 1 - (pipelined consumer wait / serial feed
     overhead), from the pipeline's own FeedStats — how much of the
     serial path's feed time the pipelined path hides under the step.

Run: python bench_data.py [--quick]   (--quick: 1 round, no artifact)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import ray_tpu as rt
from ray_tpu._private import chaos

ROWS_PER_BLOCK = 32_768  # x4B float32 = 128KB: store-kind, never inline
NUM_BLOCKS = 24
PULL_DELAY_S = 0.010     # injected per-pull transfer delay
STEP_S = 0.005           # synthetic training step
MULTI_N = 8
MULTI_PULL_DELAY_S = 0.040


@rt.remote(resources={"feed": 1})
def _make_block(i: int, rows: int):
    import pyarrow as pa

    return pa.table({"x": np.full(rows, float(i), dtype=np.float32)})


def _remote_dataset(num_blocks: int):
    """A Dataset whose blocks live on the non-driver node."""
    import ray_tpu.data as rtd

    refs = [_make_block.remote(i, ROWS_PER_BLOCK) for i in range(num_blocks)]
    ready, _ = rt.wait(refs, num_returns=num_blocks, timeout=120)
    assert len(ready) == num_blocks
    return rtd.Dataset(refs)


def _consume(ds, prefetch_blocks: int, prefetch_batches: int) -> float:
    """Iterate all batches with a synthetic step; returns wall seconds."""
    t0 = time.perf_counter()
    n = 0
    for batch in ds.iter_batches(batch_size=ROWS_PER_BLOCK,
                                 prefetch_blocks=prefetch_blocks,
                                 prefetch_batches=prefetch_batches):
        assert len(batch["x"]) == ROWS_PER_BLOCK
        time.sleep(STEP_S)  # the "training step"
        n += 1
    assert n == NUM_BLOCKS, n
    return time.perf_counter() - t0


def probe_feed_throughput(results):
    # Fresh block sets per variant: a pulled block is local afterwards,
    # so reusing one dataset would hand the second variant a free ride.
    ds_serial = _remote_dataset(NUM_BLOCKS)
    ds_pipe = _remote_dataset(NUM_BLOCKS)
    chaos.delay_object_pulls(PULL_DELAY_S, count=100_000)

    serial_s = _consume(ds_serial, prefetch_blocks=0, prefetch_batches=0)
    pipelined_s = _consume(ds_pipe, prefetch_blocks=4, prefetch_batches=4)
    feed_stats = ds_pipe._last_feed_stats.snapshot()

    step_total = NUM_BLOCKS * STEP_S
    serial_feed_s = max(serial_s - step_total, 1e-9)  # time NOT in the step
    overlap_ratio = max(0.0, min(1.0, 1.0 - feed_stats["wait_s"] / serial_feed_s))
    entry = {
        "metric": "feed throughput serial vs pipelined",
        "blocks": NUM_BLOCKS,
        "rows_per_block": ROWS_PER_BLOCK,
        "pull_delay_ms": PULL_DELAY_S * 1e3,
        "step_ms": STEP_S * 1e3,
        "serial_s": round(serial_s, 4),
        "pipelined_s": round(pipelined_s, 4),
        "serial_batches_per_s": round(NUM_BLOCKS / serial_s, 2),
        "pipelined_batches_per_s": round(NUM_BLOCKS / pipelined_s, 2),
        "speedup": round(serial_s / pipelined_s, 2),
        "overlap_ratio": round(overlap_ratio, 3),
        "pipelined_wait_s": round(feed_stats["wait_s"], 4),
        "pipelined_stalls": feed_stats["stall_count"],
        "serial_feed_overhead_s": round(serial_feed_s, 4),
    }
    print(json.dumps(entry))
    results.append(entry)


def probe_multi_ref_get(results):
    # Again: one fresh ref set per variant.
    @rt.remote(resources={"feed": 1})
    def big(i):
        return np.full(64_000, float(i), dtype=np.float32)  # ~256KB

    def fresh_refs():
        refs = [big.remote(i) for i in range(MULTI_N)]
        ready, _ = rt.wait(refs, num_returns=MULTI_N, timeout=60)
        assert len(ready) == MULTI_N
        return refs

    refs_serial = fresh_refs()
    refs_par = fresh_refs()
    chaos.delay_object_pulls(MULTI_PULL_DELAY_S, count=100_000)

    t0 = time.perf_counter()
    for r in refs_serial:  # the pre-refactor one-blocking-pull-at-a-time shape
        rt.get(r, timeout=30)
    serial_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    rt.get(refs_par, timeout=30)
    parallel_s = time.perf_counter() - t0

    entry = {
        "metric": "multi-ref get serial vs parallel",
        "n_refs": MULTI_N,
        "pull_delay_ms": MULTI_PULL_DELAY_S * 1e3,
        "serial_s": round(serial_s, 4),
        "parallel_s": round(parallel_s, 4),
        "speedup": round(serial_s / parallel_s, 2),
        # Injected delay rounds actually paid: N means O(N) sequential
        # probe rounds, ~1 means one concurrent round.
        "serial_probe_rounds": round(serial_s / MULTI_PULL_DELAY_S, 1),
        "parallel_probe_rounds": round(parallel_s / MULTI_PULL_DELAY_S, 1),
    }
    print(json.dumps(entry))
    results.append(entry)


def main():
    quick = "--quick" in sys.argv
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, resources={"feed": 64})
    cluster.connect()
    chaos.enable()
    results = []
    try:
        probe_feed_throughput(results)
        probe_multi_ref_get(results)
    finally:
        chaos.clear()
        chaos.disable()
        cluster.shutdown()
    if not quick:
        with open("BENCH_DATA.json", "w") as f:
            json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
