"""Paged KV engine benchmarks. Writes BENCH_PAGED_KV.json.

The paging claim is concrete: same HBM, more concurrent requests; a
resident prefix is prefill you never pay again; and the memory plane
must account for every page. Each probe gates it:

  1. mixed-length admission: a slot-pinned baseline (every request owns
     max_len rows) vs a paged engine given EXACTLY the same KV HBM
     (same row count, page-granular). Mixed traffic — a couple of long
     prompts among short ones — must reach a strictly higher peak of
     concurrently decoding requests under paging. Gate:
     paged_peak_concurrent > slotted_peak_concurrent.
  2. shared-prefix TTFT: a 224-token prompt, cold vs resubmitted while
     its pages are prefix-cache resident. The warm request skips every
     resident full page (the skipped-tokens counter must say exactly
     how many) and its TTFT must come in >= 2x faster. Gates:
     cold_ttft / warm_ttft >= 2, skipped == prompt_len - 1.
  3. head-of-line: chunked prefill + paging must keep the engine's HOL
     ledger at ~0 blocked slot-seconds across probes 1-2's traffic.
     Gate: hol_blocked_s <= 0.05.
  4. autoscaler ramp: real serve stack, signals published every 0.5 s;
     closed-loop clients ramp a 1-replica app up (signals-driven
     autoscaler must reach >= 2 replicas), then stop (back down to 1,
     reusing the PR 8 drain plane). Gates: scaled up, scaled back
     down, zero lost non-shed requests.
  5. page-leak: after probe 2's engine drains and its prefix cache is
     chaos-flushed, the pool must be exactly empty. Gate:
     pages_in_use == 0.

Run: python bench_paged_kv.py [--quick]  (--quick: no artifact).
Exits non-zero when a gate fails.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from dataclasses import replace

import numpy as np


def _tiny_model():
    import jax

    from ray_tpu.models import configs, init_params

    cfg = replace(configs.tiny, dtype=np.float32)
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def probe_mixed_length_admission(results):
    """Peak concurrent requests at equal KV HBM: paged vs slot-pinned."""
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    max_len, ps = 128, 16
    base_slots = 4
    # Equal HBM: the paged pool gets exactly the slotted cache's row
    # count (base_slots * max_len rows = base_slots * pages_per_slot
    # pages) + the reserved NULL page, but may spread it over 3x the
    # slots because short requests reserve only their own footprint.
    pages = base_slots * (max_len // ps) + 1
    prompts = ([list(range(1, 41))] * 2
               + [[7 + i, 3, 9, 1] for i in range(10)])

    peaks, hol = {}, {}
    for mode, slots, kv_pages in (("slotted", base_slots, None),
                                  ("paged", 3 * base_slots, pages)):
        eng = ContinuousBatchingEngine(
            params, cfg, num_slots=slots, max_len=max_len, kv_mode=mode,
            page_size=ps, kv_pages=kv_pages,
        )
        try:
            handles = [eng.submit(p, max_new_tokens=24) for p in prompts]
            done_evt = threading.Event()

            def waiter(hs=handles, ev=done_evt):
                for h in hs:
                    h.result(timeout=300)
                ev.set()

            w = threading.Thread(target=waiter, daemon=True)
            w.start()
            peak = 0
            while not done_evt.is_set():
                peak = max(peak, eng.stats()["active"])
                time.sleep(0.002)
            w.join(timeout=300)
            peaks[mode] = peak
            hol[mode] = eng.stats()["hol"]["blocked_slot_seconds"]
        finally:
            eng.shutdown()

    entry = {
        "metric": "mixed-length peak concurrency at equal KV HBM",
        "kv_rows_both": base_slots * max_len,
        "requests": len(prompts),
        "slotted_peak_concurrent": peaks["slotted"],
        "paged_peak_concurrent": peaks["paged"],
        "gate": "paged_peak_concurrent > slotted_peak_concurrent",
        "pass": peaks["paged"] > peaks["slotted"],
    }
    print(json.dumps(entry))
    results.append(entry)
    return hol


def probe_shared_prefix_ttft(results):
    """Cold vs prefix-cache-warm TTFT for a long shared prompt.
    Returns the engine (probe 5 reuses it for the leak gate)."""
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=2, max_len=256, kv_mode="paged",
        page_size=16, prefill_chunk=32,
    )
    prompt = [(5 * i + 2) % 50 for i in range(224)]  # 14 full pages

    def ttft(p):
        t0 = time.perf_counter()
        h = eng.submit(p, max_new_tokens=4)
        for _ in h:
            return time.perf_counter() - t0, h

    cold_s, h = ttft(prompt)
    h.result(timeout=300)
    # The insert happens at prefill completion; make sure the pages are
    # resident before the warm pass.
    deadline = time.monotonic() + 30
    while eng.stats()["kv"]["prefix_cache_pages"] < len(prompt) // 16:
        assert time.monotonic() < deadline, "prefix never cached"
        time.sleep(0.01)
    skipped_before = eng.stats()["kv"]["prefill_tokens_skipped"]
    warm_s, h = ttft(prompt)
    h.result(timeout=300)
    skipped = eng.stats()["kv"]["prefill_tokens_skipped"] - skipped_before

    speedup = cold_s / warm_s if warm_s > 0 else float("inf")
    # All 14 resident pages cover the prompt; only the final token is
    # recomputed (its logits seed generation): skip == len(prompt) - 1.
    expect_skip = len(prompt) - 1
    entry = {
        "metric": "shared-prefix TTFT: cold vs prefix-cache hit",
        "prompt_tokens": len(prompt),
        "prefill_chunk": 32,
        "cold_ttft_ms": round(cold_s * 1e3, 2),
        "warm_ttft_ms": round(warm_s * 1e3, 2),
        "speedup": round(speedup, 2),
        "prefill_tokens_skipped": skipped,
        "gate": f"speedup >= 2 and prefill_tokens_skipped == {expect_skip}",
        "pass": speedup >= 2.0 and skipped == expect_skip,
    }
    print(json.dumps(entry))
    results.append(entry)
    return eng


def probe_hol(results, hol_by_mode, eng2):
    """Chunked prefill + paging keep head-of-line blocking at ~0."""
    total = (hol_by_mode.get("paged", 0.0)
             + eng2.stats()["hol"]["blocked_slot_seconds"])
    entry = {
        "metric": "head-of-line blocking across paged probes",
        "hol_blocked_s": round(total, 4),
        "gate": "hol_blocked_s <= 0.05",
        "pass": total <= 0.05,
    }
    print(json.dumps(entry))
    results.append(entry)


def probe_page_leak(results, eng):
    """Drain + chaos-flush the prefix cache: the pool must hit zero."""
    from ray_tpu._private import chaos

    chaos.enable()
    try:
        held_before = eng.stats()["kv"]["prefix_cache_pages"]
        chaos.flush_prefix_cache()
        deadline = time.monotonic() + 30
        while True:
            kv = eng.stats()["kv"]
            if kv["pages_in_use"] == 0:
                break
            if time.monotonic() > deadline:
                break
            time.sleep(0.02)
    finally:
        chaos.disable()
        chaos.clear()
        eng.shutdown()
    entry = {
        "metric": "page-leak: pool empty after drain + cache flush",
        "cache_pages_flushed": held_before,
        "pages_in_use_after": kv["pages_in_use"],
        "prefix_cache_pages_after": kv["prefix_cache_pages"],
        "gate": "pages_in_use_after == 0",
        "pass": kv["pages_in_use"] == 0,
    }
    print(json.dumps(entry))
    results.append(entry)


def probe_autoscaler_ramp(results, quick: bool):
    """Signals-driven autoscaler tracks a traffic ramp up and down."""
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu._private.config import get_config

    cfg = get_config()
    saved = cfg.serve_signals_interval_s
    cfg.serve_signals_interval_s = 0.5
    rt.init(num_cpus=8)
    try:
        @serve.deployment(
            num_replicas=1,
            max_ongoing_requests=4,
            autoscaling_config=serve.AutoscalingConfig(
                min_replicas=1, max_replicas=3,
                target_ongoing_requests=1,
                upscale_delay_s=0.2, downscale_delay_s=1.0,
                upscale_queue_depth=0.5,
            ),
        )
        class Slowish:
            def __call__(self, x=0):
                time.sleep(0.25)
                return x

        serve.run(Slowish.bind(), name="ramp")
        handle = serve.get_app_handle("ramp")
        assert handle.remote(0).result(timeout=60) == 0

        ok, lost, shed = [0], [], [0]
        stop = threading.Event()

        def pump():
            from ray_tpu.exceptions import ServeOverloadedError

            while not stop.is_set():
                try:
                    if handle.remote(1).result(timeout=60) == 1:
                        ok[0] += 1
                except ServeOverloadedError:
                    shed[0] += 1
                except Exception as e:  # noqa: BLE001 — tally, gate below
                    lost.append(f"{type(e).__name__}: {e}")

        def replicas():
            return len(rt.get(
                serve.get_or_create_controller().get_replicas.remote(
                    "ramp"), timeout=10)["replicas"])

        threads = [threading.Thread(target=pump, daemon=True)
                   for _ in range(6)]
        for t in threads:
            t.start()
        peak, up_s = 1, None
        t0 = time.monotonic()
        deadline = t0 + (30 if quick else 60)
        try:
            while time.monotonic() < deadline:
                n = replicas()
                peak = max(peak, n)
                if n >= 2 and up_s is None:
                    up_s = time.monotonic() - t0
                if up_s is not None and time.monotonic() - t0 > up_s + 2:
                    break
                time.sleep(0.25)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
        # Idle: the autoscaler must walk back down to min_replicas,
        # draining the excess replicas gracefully (PR 8 drain plane).
        down = False
        deadline = time.monotonic() + (30 if quick else 60)
        while time.monotonic() < deadline:
            if replicas() == 1:
                down = True
                break
            time.sleep(0.5)
        entry = {
            "metric": "signals-driven autoscaler ramp up/down",
            "signals_interval_s": 0.5,
            "requests_ok": ok[0],
            "shed": shed[0],
            "lost_non_shed": len(lost),
            "lost_samples": lost[:5],
            "peak_replicas": peak,
            "scale_up_s": round(up_s, 2) if up_s is not None else None,
            "scaled_back_down": down,
            "gate": "peak_replicas >= 2 and scaled_back_down and "
                    "lost_non_shed == 0",
            "pass": peak >= 2 and down and not lost,
        }
        print(json.dumps(entry))
        results.append(entry)
        serve.delete("ramp")
    finally:
        serve.shutdown()
        rt.shutdown()
        cfg.serve_signals_interval_s = saved


def main():
    quick = "--quick" in sys.argv
    results = []
    hol_by_mode = probe_mixed_length_admission(results)
    eng2 = probe_shared_prefix_ttft(results)
    probe_hol(results, hol_by_mode, eng2)
    probe_autoscaler_ramp(results, quick)
    probe_page_leak(results, eng2)
    if not quick:
        with open("BENCH_PAGED_KV.json", "w") as f:
            json.dump(results, f, indent=1)
    failed = [r["metric"] for r in results if r.get("pass") is False]
    if failed:
        print(f"GATE FAILURES: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
