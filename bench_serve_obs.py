"""Serve request-observatory overhead benchmarks. Writes BENCH_SERVE_OBS.json.

Always-on per-request phase attribution is only defensible if the
serving path cannot feel it, so this bench measures exactly that —
three probes, each with an explicit pass/fail gate:

  1. steady-state decode overhead: the SAME ContinuousBatchingEngine
     serves identical long-decode requests with the observatory attached
     (wire ctx -> begin -> engine stamps -> finish) vs disabled (every
     hop short-circuits on the config flag). Measured as ms/token in
     MANY strictly adjacent off/on pairs, taking the MEDIAN of per-pair
     overhead ratios: single-request wall on a shared-box CPU is
     heavy-tailed (scheduler bursts swing one 30ms request +-20%), so
     no absolute-median comparison at a feasible sample count resolves
     a sub-1% effect — but per-pair ratios are drift-free and their
     median converges ~1/sqrt(pairs). The in-pair lead alternates so
     second-slot effects cancel too, and GC is collected then disabled
     around the timed window so collector pauses land on whichever arm
     is unlucky, not on the code path under test.
     Gate: overhead_pct < 2 (MIGRATION.md pins this).
  2. phase-sum coverage: over the on-arm's finished requests, the mean
     fraction of e2e wall explained by the phase vector. Gate: >= 0.95
     (by construction it is 1.0; the gate catches stamp-wiring
     regressions, e.g. a hop that stops stamping).
  3. HOL true-positive probe: chaos-stretch one prefill pass while a
     request is decoding; the watchdog must record the event AND blame
     the prefilling request. Gate: attributed == true.

Plus the absolute per-request price (begin + marks + finish + ring +
metrics) in microseconds, measured on synthetic requests with no engine
to hide behind.

Run: python bench_serve_obs.py [--quick]  (--quick: fewer requests, no
artifact). Exits non-zero when a gate fails.
"""

from __future__ import annotations

import gc
import json
import statistics
import sys
import time

PAIRS = 150               # adjacent off/on request pairs
MAX_NEW_TOKENS = 64       # decode length per request
SYNTH_REQUESTS = 2000


def _tiny_engine():
    from dataclasses import replace

    import jax
    import numpy as np

    from ray_tpu.models import configs, init_params
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    cfg = replace(configs.tiny, dtype=np.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return ContinuousBatchingEngine(params, cfg, num_slots=2, max_len=128)


def _one_request(eng, observed: bool, max_new_tokens: int):
    """One engine request end to end; with the observatory on, walk the
    full replica-path ctx dance (wire ctx -> begin -> finish). Returns
    seconds per generated token."""
    from ray_tpu.serve import observatory

    t0 = time.perf_counter()
    ctx = None
    if observed:
        w = observatory.make_wire_ctx("bench")
        w["disp_t"] = time.time()
        ctx = observatory.begin(w, "bench-app", "__call__")
    h = eng.submit([3, 7, 11, 2], max_new_tokens=max_new_tokens)
    h.result(timeout=300)
    if observed:
        observatory.finish(ctx)
    return (time.perf_counter() - t0) / max_new_tokens


def probe_engine_overhead(results, quick: bool):
    from ray_tpu._private.config import get_config
    from ray_tpu.serve import observatory

    observatory.reset_for_tests()
    observatory.configure("bench-app", None)
    cfg = get_config()
    eng = _tiny_engine()
    pairs = 20 if quick else PAIRS
    mnt = 32 if quick else MAX_NEW_TOKENS
    off_ts, on_ts = [], []

    def _timed(observed):
        cfg.serve_observatory = observed
        return _one_request(eng, observed, mnt)

    try:
        # Warm both arms (first requests pay admission/prefill warmup).
        _timed(False)
        _timed(True)
        gc.collect()
        gc.disable()
        for p in range(pairs):
            # Alternate which arm leads inside the pair so any residual
            # first-slot advantage cancels across pairs too.
            if p % 2:
                on_ts.append(_timed(True))
                off_ts.append(_timed(False))
            else:
                off_ts.append(_timed(False))
                on_ts.append(_timed(True))
    finally:
        gc.enable()
        cfg.serve_observatory = True
        eng.shutdown()
    pair_pct = [
        (on - off) / off * 100.0 for off, on in zip(off_ts, on_ts)
    ]
    overhead_pct = statistics.median(pair_pct)
    entry = {
        "metric": "observatory steady-state decode overhead "
                  "(median of paired off/on ratios)",
        "pairs": pairs,
        "max_new_tokens": mnt,
        "off_ms_per_token_p50": round(
            statistics.median(off_ts) * 1e3, 4),
        "on_ms_per_token_p50": round(statistics.median(on_ts) * 1e3, 4),
        "pair_overhead_pct_quartiles": [
            round(statistics.quantiles(pair_pct, n=4)[i], 3)
            for i in range(3)
        ],
        "overhead_pct": round(overhead_pct, 3),
        "gate": "overhead_pct < 2",
        "pass": overhead_pct < 2.0,
    }
    print(json.dumps(entry))
    results.append(entry)

    # Phase-sum coverage over the on-arm's finished requests.
    recs = observatory.profiler().records()
    fractions = [
        sum(r["phases"].values()) / r["e2e_s"] for r in recs if r["e2e_s"] > 0
    ]
    mean_frac = sum(fractions) / len(fractions) if fractions else 0.0
    entry = {
        "metric": "phase-sum fraction of request e2e",
        "requests": len(fractions),
        "mean_fraction": round(mean_frac, 6),
        "min_fraction": round(min(fractions), 6) if fractions else 0.0,
        "gate": "mean_fraction >= 0.95",
        "pass": mean_frac >= 0.95,
    }
    print(json.dumps(entry))
    results.append(entry)


def probe_synthetic_request_cost(results, quick: bool):
    """Absolute observatory price per request, nothing to hide behind:
    wire ctx + begin + the six stamps + finish (ring append, phase
    computation, metric emission, tenant scoring)."""
    from ray_tpu.serve import observatory
    from ray_tpu.serve.deployment import SloConfig

    observatory.reset_for_tests()
    observatory.configure("synth", SloConfig(e2e_ms=100.0))
    n = 200 if quick else SYNTH_REQUESTS
    t0 = time.perf_counter()
    for _ in range(n):
        w = observatory.make_wire_ctx("t")
        w["disp_t"] = time.time()
        ctx = observatory.begin(w, "synth", "__call__")
        ctx.mark("engine_enqueue")
        ctx.mark("slot_grant")
        ctx.mark("first_token")
        ctx.tokens_in = 8
        ctx.tokens_out = 16
        ctx.mark("engine_done")
        observatory.finish(ctx)
    cost_us = (time.perf_counter() - t0) / n * 1e6
    entry = {
        "metric": "observatory cost, synthetic requests",
        "requests": n,
        "cost_us_per_request": round(cost_us, 2),
    }
    print(json.dumps(entry))
    results.append(entry)


def probe_hol_true_positive(results, quick: bool):
    """Inject one chaos-stretched prefill behind an active decode; the
    watchdog must see it and blame the right request."""
    from ray_tpu._private import chaos

    eng = _tiny_engine()
    chaos.enable()
    try:
        long_h = eng.submit([3, 7, 11, 2], max_new_tokens=80)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            s = eng.stats()
            if s["active"] == 1 and s["prefilling"] == 0:
                break
            time.sleep(0.01)
        chaos.delay_prefills(0.2, count=1)
        blocker = eng.submit([5, 1, 8, 2], max_new_tokens=4)
        blocker.result(timeout=120)
        long_h.result(timeout=120)
        hol = eng.stats()["hol"]
    finally:
        chaos.disable()
        chaos.clear()
        eng.shutdown()
    ev = hol["events"][0] if hol["events"] else None
    attributed = bool(
        ev and blocker.request_id in
        [c["request_id"] for c in ev["culprits"]]
    )
    entry = {
        "metric": "HOL watchdog true-positive probe",
        "injected_prefill_s": 0.2,
        "events_recorded": len(hol["events"]),
        "blocked_slot_seconds": round(hol["blocked_slot_seconds"], 4),
        "victims": ev["victims"] if ev else 0,
        "attributed_to_injected_request": attributed,
        "gate": "attributed_to_injected_request == true",
        "pass": attributed,
    }
    print(json.dumps(entry))
    results.append(entry)


def main():
    quick = "--quick" in sys.argv
    results = []
    probe_engine_overhead(results, quick)
    probe_synthetic_request_cost(results, quick)
    probe_hol_true_positive(results, quick)
    if not quick:
        with open("BENCH_SERVE_OBS.json", "w") as f:
            json.dump(results, f, indent=1)
    failed = [r["metric"] for r in results if r.get("pass") is False]
    if failed:
        print(f"GATE FAILURES: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
