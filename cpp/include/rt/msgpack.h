// Minimal msgpack value model + codec for the rt C++ client.
//
// Covers the subset the rt wire protocol uses (protocol.py frame maps:
// nil, bool, int, float64, str, bin, array, map). Reference analog: the
// C++ user API's serialization layer (cpp/include/ray/api/serializer.h in
// the reference uses msgpack too).

#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <optional>
#include <string>
#include <utility>
#include <vector>

namespace rt {

class Value {
 public:
  enum class Type { kNil, kBool, kInt, kUint, kFloat, kStr, kBin, kArr, kMap };

  Value() : type_(Type::kNil) {}

  static Value Nil() { return Value(); }
  static Value B(bool b) {
    Value v;
    v.type_ = Type::kBool;
    v.b_ = b;
    return v;
  }
  static Value I(int64_t i) {
    Value v;
    v.type_ = Type::kInt;
    v.i_ = i;
    return v;
  }
  static Value U(uint64_t u) {
    Value v;
    v.type_ = Type::kUint;
    v.u_ = u;
    return v;
  }
  static Value F(double d) {
    Value v;
    v.type_ = Type::kFloat;
    v.d_ = d;
    return v;
  }
  static Value S(std::string s) {
    Value v;
    v.type_ = Type::kStr;
    v.s_ = std::move(s);
    return v;
  }
  static Value Bin(std::string bytes) {
    Value v;
    v.type_ = Type::kBin;
    v.s_ = std::move(bytes);
    return v;
  }
  static Value Arr(std::vector<Value> items = {}) {
    Value v;
    v.type_ = Type::kArr;
    v.arr_ = std::move(items);
    return v;
  }
  static Value Map() {
    Value v;
    v.type_ = Type::kMap;
    return v;
  }

  Type type() const { return type_; }
  bool is_nil() const { return type_ == Type::kNil; }

  bool as_bool() const { return b_; }
  int64_t as_int() const {
    if (type_ == Type::kUint) return static_cast<int64_t>(u_);
    if (type_ == Type::kFloat) return static_cast<int64_t>(d_);
    return i_;
  }
  double as_double() const {
    if (type_ == Type::kInt) return static_cast<double>(i_);
    if (type_ == Type::kUint) return static_cast<double>(u_);
    return d_;
  }
  const std::string& as_str() const { return s_; }   // kStr or kBin
  const std::string& as_bin() const { return s_; }
  const std::vector<Value>& as_arr() const { return arr_; }
  std::vector<Value>& arr() { return arr_; }

  // Map access (string keys — the wire protocol's convention).
  Value& operator[](const std::string& key);
  const Value* find(const std::string& key) const;
  const std::vector<std::pair<Value, Value>>& as_map() const { return map_; }

  void pack(std::string* out) const;
  // Returns false on truncated/invalid input.
  static bool unpack(const uint8_t* data, size_t len, size_t* pos, Value* out);

 private:
  Type type_;
  bool b_ = false;
  int64_t i_ = 0;
  uint64_t u_ = 0;
  double d_ = 0;
  std::string s_;
  std::vector<Value> arr_;
  std::vector<std::pair<Value, Value>> map_;
};

}  // namespace rt
