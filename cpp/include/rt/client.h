// rt C++ user API: a native client for the ray_tpu runtime.
//
// Reference analog: the C++ user API (cpp/include/ray/api/ in the
// reference, ~9k LoC over the CoreWorker). This runtime's control plane
// is length-prefixed msgpack frames over TCP (ray_tpu/_private/
// protocol.py), so the native client speaks that protocol directly — no
// Python in the loop:
//
//   * cluster attach (GCS get_nodes -> head raylet), driver job
//     registration — the rt:// remote-driver role
//     (ray_tpu/__init__.py _remote_attach)
//   * GCS KV get/put/del
//   * object put/get against the head raylet's shared-memory store
//     (client_put / client_get_info / fetch_chunk), using the RTX1
//     cross-language object framing (msgpack payload) so Python
//     rt.get() reads C++ puts and vice versa
//   * cross-language task submission: Submit("module:function", args)
//     runs the named Python function in a pool worker and returns its
//     RTX1-encoded result (reference: cross-language function-descriptor
//     calls used by the Java/C++ frontends)
//
// Blocking, single-connection-per-peer; link against librt_client.a.

#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

#include "rt/msgpack.h"

namespace rt {

class Client {
 public:
  Client() = default;
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Attach to a cluster via its GCS address. Registers a driver job.
  bool Connect(const std::string& gcs_host, int gcs_port);
  void Disconnect();
  const std::string& last_error() const { return error_; }

  // -- GCS key-value store --------------------------------------------
  bool KvPut(const std::string& ns, const std::string& key,
             const std::string& value, bool overwrite = true);
  std::optional<std::string> KvGet(const std::string& ns,
                                   const std::string& key);
  bool KvDel(const std::string& ns, const std::string& key);

  // -- objects ---------------------------------------------------------
  // Put a msgpack value into the cluster object store; returns the
  // 16-byte object id ("" on failure).
  std::string Put(const Value& value);
  // Fetch + decode an RTX1 object by id.
  std::optional<Value> Get(const std::string& object_id,
                           double timeout_s = 60.0);

  // -- tasks -----------------------------------------------------------
  struct TaskResult {
    bool ok = false;
    std::string error;
    Value value;
  };
  struct ActorInfo {
    bool ok = false;
    std::string error;
    std::string actor_id;   // 16 raw bytes
    std::string address;    // worker host
    int64_t port = 0;       // worker RPC port
    std::string state;
  };
  // Resolve a named actor (reference: ray.get_actor) to its hosting
  // worker's direct-call address.
  ActorInfo GetNamedActor(const std::string& name,
                          const std::string& ns = "");
  // Direct cross-language actor method call: msgpack-plain args in,
  // RTX1 result out, straight to the actor's worker (the reference's
  // direct actor transport role for foreign frontends).
  TaskResult ActorCall(const ActorInfo& actor, const std::string& method,
                       const std::vector<Value>& args,
                       double timeout_s = 60.0);

  // Run the Python function "module:attr" in a cluster worker with
  // msgpack-plain args; blocks for the result.
  TaskResult Submit(const std::string& fn_name,
                    const std::vector<Value>& args,
                    double timeout_s = 120.0);

 private:
  TaskResult ParseTaskResult(const Value& r, double timeout_s);
  Value Call(int fd, const std::string& method, const Value& payload,
             bool* ok);
  bool SendFrame(int fd, const Value& frame);
  bool RecvFrame(int fd, Value* frame);
  std::string RandomId();

  int gcs_fd_ = -1;
  int raylet_fd_ = -1;
  int64_t next_call_id_ = 1;
  std::string job_id_;
  std::string error_;
};

}  // namespace rt
