// Unit tests for the rt msgpack codec (no gtest dependency: asserts +
// exit code, run by tests/test_cpp_client.py).
//
// Covers the format edges the Python side (msgpack-python) produces:
// fixint boundaries, every int width, negative widths, float32/64,
// str/bin length tiers, nested arrays/maps, and roundtrip stability.

#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include "rt/msgpack.h"

using rt::Value;

namespace {

Value roundtrip(const Value& v) {
  std::string buf;
  v.pack(&buf);
  Value out;
  size_t pos = 0;
  bool ok = Value::unpack(reinterpret_cast<const uint8_t*>(buf.data()),
                          buf.size(), &pos, &out);
  assert(ok && "unpack failed");
  assert(pos == buf.size() && "trailing bytes after unpack");
  return out;
}

void test_ints() {
  const int64_t cases[] = {
      0, 1, 127, 128, 255, 256, 65535, 65536, 2147483647LL, 2147483648LL,
      INT64_MAX, -1, -32, -33, -128, -129, -32768, -32769, -2147483648LL,
      -2147483649LL, INT64_MIN,
  };
  for (int64_t v : cases) {
    assert(roundtrip(Value::I(v)).as_int() == v);
  }
  // Unsigned beyond int64 survives as kUint.
  Value u = roundtrip(Value::U(UINT64_MAX));
  assert(u.type() == Value::Type::kUint || u.as_int() == -1);
}

void test_floats() {
  const double cases[] = {0.0, 1.5, -2.25, 3.14159265358979, 1e300, -1e-300};
  for (double v : cases) {
    assert(roundtrip(Value::F(v)).as_double() == v);
  }
}

void test_strings_and_bins() {
  const size_t lens[] = {0, 1, 31, 32, 255, 256, 65535, 65536};
  for (size_t n : lens) {
    std::string s(n, 'x');
    assert(roundtrip(Value::S(s)).as_str() == s);
    std::string b(n, '\0');
    if (n > 0) b[n / 2] = '\x7f';
    Value rb = roundtrip(Value::Bin(b));
    assert(rb.type() == Value::Type::kBin);
    assert(rb.as_bin() == b);
  }
}

void test_containers() {
  // Array length tiers: 0, 15, 16, 70000.
  for (size_t n : {size_t(0), size_t(15), size_t(16), size_t(70000)}) {
    Value arr = Value::Arr();
    for (size_t i = 0; i < n; ++i) {
      arr.arr().push_back(Value::I(static_cast<int64_t>(i % 1000)));
    }
    Value out = roundtrip(arr);
    assert(out.as_arr().size() == n);
    if (n > 3) assert(out.as_arr()[3].as_int() == 3);
  }
  // Nested map with every scalar type.
  Value m = Value::Map();
  m["nil"] = Value::Nil();
  m["yes"] = Value::B(true);
  m["n"] = Value::I(-42);
  m["f"] = Value::F(2.5);
  m["s"] = Value::S("hello");
  m["b"] = Value::Bin(std::string("\x00\x01", 2));
  Value inner = Value::Map();
  inner["deep"] = Value::Arr({Value::I(1), Value::S("two")});
  m["obj"] = inner;
  Value out = roundtrip(m);
  assert(out.find("nil")->is_nil());
  assert(out.find("yes")->as_bool());
  assert(out.find("n")->as_int() == -42);
  assert(out.find("f")->as_double() == 2.5);
  assert(out.find("s")->as_str() == "hello");
  assert(out.find("b")->as_bin().size() == 2);
  assert(out.find("obj")->find("deep")->as_arr()[1].as_str() == "two");
}

void test_truncation_rejected() {
  Value m = Value::Map();
  m["key"] = Value::S("a longer value here");
  std::string buf;
  m.pack(&buf);
  // Every proper prefix must fail cleanly, never crash or succeed.
  for (size_t cut = 0; cut + 1 < buf.size(); ++cut) {
    Value out;
    size_t pos = 0;
    bool ok = Value::unpack(reinterpret_cast<const uint8_t*>(buf.data()),
                            cut, &pos, &out);
    assert(!ok || pos <= cut);
  }
}

}  // namespace

int main() {
  test_ints();
  test_floats();
  test_strings_and_bins();
  test_containers();
  test_truncation_rejected();
  std::printf("MSGPACK TESTS OK\n");
  return 0;
}
