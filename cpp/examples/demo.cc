// End-to-end demo/test for the rt C++ user API.
//
// Usage: rt_demo <gcs_host> <gcs_port>
// Prints "CPP CLIENT OK" and exits 0 when every step passes; the Python
// test harness (tests/test_cpp_client.py) drives this against a live
// cluster.

#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "rt/client.h"

#define CHECK(cond, what)                                         \
  do {                                                            \
    if (!(cond)) {                                                \
      std::fprintf(stderr, "FAIL %s: %s\n", what,                 \
                   client.last_error().c_str());                  \
      return 1;                                                   \
    }                                                             \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <gcs_host> <gcs_port> [actor_name]\n",
                 argv[0]);
    return 2;
  }
  rt::Client client;
  CHECK(client.Connect(argv[1], std::atoi(argv[2])), "connect");

  // 1. GCS KV round trip.
  CHECK(client.KvPut("cpp", "greeting", "hello from c++"), "kv_put");
  auto got = client.KvGet("cpp", "greeting");
  CHECK(got.has_value() && *got == "hello from c++", "kv_get");
  CHECK(client.KvDel("cpp", "greeting"), "kv_del");
  CHECK(!client.KvGet("cpp", "greeting").has_value(), "kv_del_took");

  // 2. Object store put/get round trip (RTX1 cross-language framing).
  rt::Value obj = rt::Value::Map();
  obj["kind"] = rt::Value::S("cpp-object");
  obj["payload"] = rt::Value::Arr({rt::Value::I(1), rt::Value::I(2),
                                   rt::Value::F(3.5)});
  std::string oid = client.Put(obj);
  CHECK(!oid.empty(), "put");
  auto fetched = client.Get(oid);
  CHECK(fetched.has_value(), "get");
  CHECK(fetched->find("kind")->as_str() == "cpp-object", "get_roundtrip");
  CHECK(fetched->find("payload")->as_arr()[2].as_double() == 3.5,
        "get_payload");

  // 3. Cross-language task: run Python math.hypot(3, 4) in a worker.
  auto result = client.Submit("math:hypot",
                              {rt::Value::F(3.0), rt::Value::F(4.0)});
  if (!result.ok) {
    std::fprintf(stderr, "FAIL submit: %s\n", result.error.c_str());
    return 1;
  }
  if (result.value.as_double() != 5.0) {
    std::fprintf(stderr, "FAIL submit value: %f\n",
                 result.value.as_double());
    return 1;
  }

  // 4. Cross-language task returning a structure.
  auto sorted = client.Submit(
      "builtins:sorted",
      {rt::Value::Arr({rt::Value::I(3), rt::Value::I(1), rt::Value::I(2)})});
  if (!sorted.ok) {
    std::fprintf(stderr, "FAIL sorted: %s\n", sorted.error.c_str());
    return 1;
  }
  const auto& arr = sorted.value.as_arr();
  if (arr.size() != 3 || arr[0].as_int() != 1 || arr[2].as_int() != 3) {
    std::fprintf(stderr, "FAIL sorted value\n");
    return 1;
  }

  // 5. A failing task surfaces its Python error.
  auto bad = client.Submit("math:sqrt", {rt::Value::S("not-a-number")});
  if (bad.ok) {
    std::fprintf(stderr, "FAIL error propagation: bad task succeeded\n");
    return 1;
  }

  // 6. Direct cross-language actor call (optional: pass the name of a
  // live named actor as argv[3]; the Python harness creates one).
  if (argc >= 4) {
    auto actor = client.GetNamedActor(argv[3]);
    if (!actor.ok) {
      std::fprintf(stderr, "FAIL get_named_actor: %s\n",
                   actor.error.c_str());
      return 1;
    }
    auto r1 = client.ActorCall(actor, "add", {rt::Value::I(40)});
    if (!r1.ok || r1.value.as_int() != 40) {
      std::fprintf(stderr, "FAIL actor add: %s\n", r1.error.c_str());
      return 1;
    }
    auto r2 = client.ActorCall(actor, "add", {rt::Value::I(2)});
    if (!r2.ok || r2.value.as_int() != 42) {
      std::fprintf(stderr, "FAIL actor state: %s (got %lld)\n",
                   r2.error.c_str(),
                   static_cast<long long>(r2.value.as_int()));
      return 1;
    }
    auto r3 = client.ActorCall(actor, "nope", {});
    if (r3.ok) {
      std::fprintf(stderr, "FAIL actor error propagation\n");
      return 1;
    }
    std::printf("CPP ACTOR OK\n");
  }

  std::printf("CPP CLIENT OK\n");
  return 0;
}
