// msgpack codec — see include/rt/msgpack.h.

#include "rt/msgpack.h"

#include <algorithm>
#include <cstring>

namespace rt {

Value& Value::operator[](const std::string& key) {
  type_ = Type::kMap;
  for (auto& kv : map_) {
    if (kv.first.type() == Type::kStr && kv.first.as_str() == key) {
      return kv.second;
    }
  }
  map_.emplace_back(Value::S(key), Value());
  return map_.back().second;
}

const Value* Value::find(const std::string& key) const {
  for (const auto& kv : map_) {
    if (kv.first.type() == Type::kStr && kv.first.as_str() == key) {
      return &kv.second;
    }
  }
  return nullptr;
}

namespace {

void put_u8(std::string* out, uint8_t b) { out->push_back(static_cast<char>(b)); }

void put_be(std::string* out, uint64_t v, int bytes) {
  for (int i = bytes - 1; i >= 0; --i) {
    out->push_back(static_cast<char>((v >> (8 * i)) & 0xff));
  }
}

bool read_be(const uint8_t* data, size_t len, size_t* pos, int bytes,
             uint64_t* out) {
  if (*pos + bytes > len) return false;
  uint64_t v = 0;
  for (int i = 0; i < bytes; ++i) v = (v << 8) | data[(*pos)++];
  *out = v;
  return true;
}

}  // namespace

void Value::pack(std::string* out) const {
  switch (type_) {
    case Type::kNil:
      put_u8(out, 0xc0);
      break;
    case Type::kBool:
      put_u8(out, b_ ? 0xc3 : 0xc2);
      break;
    case Type::kInt: {
      int64_t i = i_;
      if (i >= 0) {
        if (i < 128) {
          put_u8(out, static_cast<uint8_t>(i));
        } else if (i <= 0xffff) {
          put_u8(out, 0xcd);
          put_be(out, static_cast<uint64_t>(i), 2);
        } else if (i <= 0xffffffffLL) {
          put_u8(out, 0xce);
          put_be(out, static_cast<uint64_t>(i), 4);
        } else {
          put_u8(out, 0xcf);
          put_be(out, static_cast<uint64_t>(i), 8);
        }
      } else {
        if (i >= -32) {
          put_u8(out, static_cast<uint8_t>(0xe0 | (i + 32)));
        } else if (i >= -32768) {
          put_u8(out, 0xd1);
          put_be(out, static_cast<uint16_t>(i), 2);
        } else if (i >= -2147483648LL) {
          put_u8(out, 0xd2);
          put_be(out, static_cast<uint32_t>(i), 4);
        } else {
          put_u8(out, 0xd3);
          put_be(out, static_cast<uint64_t>(i), 8);
        }
      }
      break;
    }
    case Type::kUint:
      put_u8(out, 0xcf);
      put_be(out, u_, 8);
      break;
    case Type::kFloat: {
      put_u8(out, 0xcb);
      uint64_t bits;
      static_assert(sizeof(bits) == sizeof(d_), "double must be 8 bytes");
      std::memcpy(&bits, &d_, 8);
      put_be(out, bits, 8);
      break;
    }
    case Type::kStr: {
      size_t n = s_.size();
      if (n < 32) {
        put_u8(out, static_cast<uint8_t>(0xa0 | n));
      } else if (n <= 0xff) {
        put_u8(out, 0xd9);
        put_be(out, n, 1);
      } else if (n <= 0xffff) {
        put_u8(out, 0xda);
        put_be(out, n, 2);
      } else {
        put_u8(out, 0xdb);
        put_be(out, n, 4);
      }
      out->append(s_);
      break;
    }
    case Type::kBin: {
      size_t n = s_.size();
      if (n <= 0xff) {
        put_u8(out, 0xc4);
        put_be(out, n, 1);
      } else if (n <= 0xffff) {
        put_u8(out, 0xc5);
        put_be(out, n, 2);
      } else {
        put_u8(out, 0xc6);
        put_be(out, n, 4);
      }
      out->append(s_);
      break;
    }
    case Type::kArr: {
      size_t n = arr_.size();
      if (n < 16) {
        put_u8(out, static_cast<uint8_t>(0x90 | n));
      } else if (n <= 0xffff) {
        put_u8(out, 0xdc);
        put_be(out, n, 2);
      } else {
        put_u8(out, 0xdd);
        put_be(out, n, 4);
      }
      for (const auto& v : arr_) v.pack(out);
      break;
    }
    case Type::kMap: {
      size_t n = map_.size();
      if (n < 16) {
        put_u8(out, static_cast<uint8_t>(0x80 | n));
      } else if (n <= 0xffff) {
        put_u8(out, 0xde);
        put_be(out, n, 2);
      } else {
        put_u8(out, 0xdf);
        put_be(out, n, 4);
      }
      for (const auto& kv : map_) {
        kv.first.pack(out);
        kv.second.pack(out);
      }
      break;
    }
  }
}

bool Value::unpack(const uint8_t* data, size_t len, size_t* pos, Value* out) {
  if (*pos >= len) return false;
  uint8_t tag = data[(*pos)++];
  uint64_t n = 0;

  auto read_raw = [&](size_t count, std::string* s) -> bool {
    if (*pos + count > len) return false;
    s->assign(reinterpret_cast<const char*>(data + *pos), count);
    *pos += count;
    return true;
  };
  auto read_seq = [&](size_t count, bool map) -> bool {
    // A hostile/truncated array32 or map32 header can claim up to 2^32-1
    // elements; bound the speculative reserve by what the remaining input
    // could possibly hold (>=1 byte per element, 2 per map entry) so a bad
    // header yields a clean `false` from the element loop, not bad_alloc.
    const size_t remaining = len - *pos;
    const size_t reserve_cap =
        std::min<size_t>(count, map ? remaining / 2 : remaining);
    if (map) {
      out->type_ = Type::kMap;
      out->map_.reserve(reserve_cap);
      for (size_t i = 0; i < count; ++i) {
        Value k, v;
        if (!unpack(data, len, pos, &k) || !unpack(data, len, pos, &v)) {
          return false;
        }
        out->map_.emplace_back(std::move(k), std::move(v));
      }
    } else {
      out->type_ = Type::kArr;
      out->arr_.reserve(reserve_cap);
      for (size_t i = 0; i < count; ++i) {
        Value v;
        if (!unpack(data, len, pos, &v)) return false;
        out->arr_.push_back(std::move(v));
      }
    }
    return true;
  };

  if (tag < 0x80) {  // positive fixint
    out->type_ = Type::kInt;
    out->i_ = tag;
    return true;
  }
  if (tag >= 0xe0) {  // negative fixint
    out->type_ = Type::kInt;
    out->i_ = static_cast<int8_t>(tag);
    return true;
  }
  if ((tag & 0xe0) == 0xa0) {  // fixstr
    out->type_ = Type::kStr;
    return read_raw(tag & 0x1f, &out->s_);
  }
  if ((tag & 0xf0) == 0x90) return read_seq(tag & 0x0f, false);  // fixarray
  if ((tag & 0xf0) == 0x80) return read_seq(tag & 0x0f, true);   // fixmap

  switch (tag) {
    case 0xc0:
      out->type_ = Type::kNil;
      return true;
    case 0xc2:
    case 0xc3:
      out->type_ = Type::kBool;
      out->b_ = (tag == 0xc3);
      return true;
    case 0xc4:
    case 0xc5:
    case 0xc6: {
      int width = 1 << (tag - 0xc4);
      if (!read_be(data, len, pos, width, &n)) return false;
      out->type_ = Type::kBin;
      return read_raw(n, &out->s_);
    }
    case 0xca: {  // float32
      if (!read_be(data, len, pos, 4, &n)) return false;
      float f;
      uint32_t bits = static_cast<uint32_t>(n);
      std::memcpy(&f, &bits, 4);
      out->type_ = Type::kFloat;
      out->d_ = f;
      return true;
    }
    case 0xcb: {  // float64
      if (!read_be(data, len, pos, 8, &n)) return false;
      out->type_ = Type::kFloat;
      std::memcpy(&out->d_, &n, 8);
      return true;
    }
    case 0xcc:
    case 0xcd:
    case 0xce:
    case 0xcf: {  // uint 8/16/32/64
      int width = 1 << (tag - 0xcc);
      if (!read_be(data, len, pos, width, &n)) return false;
      if (tag == 0xcf && n > INT64_MAX) {
        out->type_ = Type::kUint;
        out->u_ = n;
      } else {
        out->type_ = Type::kInt;
        out->i_ = static_cast<int64_t>(n);
      }
      return true;
    }
    case 0xd0:
    case 0xd1:
    case 0xd2:
    case 0xd3: {  // int 8/16/32/64
      int width = 1 << (tag - 0xd0);
      if (!read_be(data, len, pos, width, &n)) return false;
      out->type_ = Type::kInt;
      switch (width) {
        case 1: out->i_ = static_cast<int8_t>(n); break;
        case 2: out->i_ = static_cast<int16_t>(n); break;
        case 4: out->i_ = static_cast<int32_t>(n); break;
        default: out->i_ = static_cast<int64_t>(n); break;
      }
      return true;
    }
    case 0xd9:
    case 0xda:
    case 0xdb: {  // str 8/16/32
      int width = 1 << (tag - 0xd9);
      if (!read_be(data, len, pos, width, &n)) return false;
      out->type_ = Type::kStr;
      return read_raw(n, &out->s_);
    }
    case 0xdc:
    case 0xdd: {  // array 16/32
      int width = tag == 0xdc ? 2 : 4;
      if (!read_be(data, len, pos, width, &n)) return false;
      return read_seq(n, false);
    }
    case 0xde:
    case 0xdf: {  // map 16/32
      int width = tag == 0xde ? 2 : 4;
      if (!read_be(data, len, pos, width, &n)) return false;
      return read_seq(n, true);
    }
    default:
      return false;  // ext types unused by the rt protocol
  }
}

}  // namespace rt
