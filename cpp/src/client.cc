// rt C++ client — see include/rt/client.h.

#include "rt/client.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstring>
#include <random>

namespace rt {

namespace {

int DialTcp(const std::string& host, int port, std::string* err) {
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  int rc = getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res);
  if (rc != 0) {
    *err = "resolve " + host + ": " + gai_strerror(rc);
    return -1;
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    *err = "connect " + host + ":" + port_s + " failed";
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

bool WriteAll(int fd, const char* data, size_t len) {
  while (len > 0) {
    ssize_t n = write(fd, data, len);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool ReadAll(int fd, char* data, size_t len) {
  while (len > 0) {
    ssize_t n = read(fd, data, len);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

}  // namespace

Client::~Client() { Disconnect(); }

void Client::Disconnect() {
  if (gcs_fd_ >= 0) close(gcs_fd_);
  if (raylet_fd_ >= 0) close(raylet_fd_);
  gcs_fd_ = raylet_fd_ = -1;
}

std::string Client::RandomId() {
  static std::mt19937_64 rng{std::random_device{}()};
  std::string id(16, '\0');
  for (int i = 0; i < 16; i += 8) {
    uint64_t r = rng();
    std::memcpy(&id[i], &r, 8);
  }
  return id;
}

bool Client::SendFrame(int fd, const Value& frame) {
  std::string body;
  frame.pack(&body);
  uint32_t len = static_cast<uint32_t>(body.size());
  char header[4];
  std::memcpy(header, &len, 4);  // protocol uses little-endian u32
  return WriteAll(fd, header, 4) && WriteAll(fd, body.data(), body.size());
}

bool Client::RecvFrame(int fd, Value* frame) {
  char header[4];
  if (!ReadAll(fd, header, 4)) return false;
  uint32_t len;
  std::memcpy(&len, header, 4);
  std::string body(len, '\0');
  if (!ReadAll(fd, &body[0], len)) return false;
  size_t pos = 0;
  return Value::unpack(reinterpret_cast<const uint8_t*>(body.data()),
                       body.size(), &pos, frame);
}

Value Client::Call(int fd, const std::string& method, const Value& payload,
                   bool* ok) {
  *ok = false;
  int64_t cid = next_call_id_++;
  Value frame = Value::Map();
  frame["k"] = Value::S("req");
  frame["i"] = Value::I(cid);
  frame["m"] = Value::S(method);
  frame["d"] = payload;
  if (!SendFrame(fd, frame)) {
    error_ = "send failed on method " + method;
    return Value::Nil();
  }
  // Blocking single-outstanding-call loop; push frames are skipped.
  while (true) {
    Value resp;
    if (!RecvFrame(fd, &resp)) {
      error_ = "connection lost awaiting " + method;
      return Value::Nil();
    }
    const Value* kind = resp.find("k");
    if (kind == nullptr || kind->as_str() != "resp") continue;
    const Value* id = resp.find("i");
    if (id == nullptr || id->as_int() != cid) continue;
    const Value* err = resp.find("e");
    if (err != nullptr && !err->is_nil()) {
      error_ = err->as_str();
      return Value::Nil();
    }
    *ok = true;
    const Value* data = resp.find("d");
    return data == nullptr ? Value::Nil() : *data;
  }
}

bool Client::Connect(const std::string& gcs_host, int gcs_port) {
  gcs_fd_ = DialTcp(gcs_host, gcs_port, &error_);
  if (gcs_fd_ < 0) return false;
  bool ok = false;
  Value nodes = Call(gcs_fd_, "get_nodes", Value::Map(), &ok);
  if (!ok) return false;
  const Value* list = nodes.find("nodes");
  if (list == nullptr) {
    error_ = "get_nodes returned no node list";
    return false;
  }
  // Prefer the head node (the rt:// attach rule, __init__._remote_attach).
  const Value* chosen = nullptr;
  for (const auto& node : list->as_arr()) {
    const Value* state = node.find("state");
    if (state == nullptr || state->as_str() != "ALIVE") continue;
    const Value* head = node.find("is_head");
    if (chosen == nullptr || (head != nullptr && head->as_bool())) {
      chosen = &node;
      if (head != nullptr && head->as_bool()) break;
    }
  }
  if (chosen == nullptr) {
    error_ = "no live nodes in cluster";
    return false;
  }
  const Value* addr = chosen->find("address");
  const Value* port = chosen->find("port");
  raylet_fd_ = DialTcp(addr->as_str(), static_cast<int>(port->as_int()),
                       &error_);
  if (raylet_fd_ < 0) return false;

  job_id_ = RandomId();
  Value reg = Value::Map();
  reg["job_id"] = Value::Bin(job_id_);
  reg["pid"] = Value::I(static_cast<int64_t>(getpid()));
  reg["entrypoint"] = Value::S("cpp-client");
  Call(gcs_fd_, "register_job", reg, &ok);
  return ok;
}

bool Client::KvPut(const std::string& ns, const std::string& key,
                   const std::string& value, bool overwrite) {
  Value d = Value::Map();
  d["ns"] = Value::S(ns);
  d["key"] = Value::Bin(key);
  d["value"] = Value::Bin(value);
  d["overwrite"] = Value::B(overwrite);
  bool ok = false;
  Value r = Call(gcs_fd_, "kv_put", d, &ok);
  if (!ok) return false;
  const Value* added = r.find("added");
  return added != nullptr && added->as_bool();
}

std::optional<std::string> Client::KvGet(const std::string& ns,
                                         const std::string& key) {
  Value d = Value::Map();
  d["ns"] = Value::S(ns);
  d["key"] = Value::Bin(key);
  bool ok = false;
  Value r = Call(gcs_fd_, "kv_get", d, &ok);
  if (!ok) return std::nullopt;
  const Value* value = r.find("value");
  if (value == nullptr || value->is_nil()) return std::nullopt;
  return value->as_bin();
}

bool Client::KvDel(const std::string& ns, const std::string& key) {
  Value d = Value::Map();
  d["ns"] = Value::S(ns);
  d["key"] = Value::Bin(key);
  bool ok = false;
  Value r = Call(gcs_fd_, "kv_del", d, &ok);
  if (!ok) return false;
  const Value* deleted = r.find("deleted");
  return deleted != nullptr && deleted->as_bool();
}

namespace {
constexpr uint32_t kXlangMagic = 0x52545831;  // "RTX1", little-endian u32
}

std::string Client::Put(const Value& value) {
  // RTX1 framing: u32 magic + msgpack payload (serialization.py).
  std::string payload(4, '\0');
  std::memcpy(&payload[0], &kXlangMagic, 4);
  value.pack(&payload);

  std::string oid = RandomId();
  Value d = Value::Map();
  d["object_id"] = Value::Bin(oid);
  d["data"] = Value::Bin(payload);
  bool ok = false;
  Value r = Call(raylet_fd_, "client_put", d, &ok);
  if (!ok) return "";
  const Value* okf = r.find("ok");
  if (okf == nullptr || !okf->as_bool()) {
    const Value* err = r.find("error");
    error_ = err != nullptr && !err->is_nil() ? err->as_str() : "put failed";
    return "";
  }
  return oid;
}

std::optional<Value> Client::Get(const std::string& object_id,
                                 double timeout_s) {
  Value d = Value::Map();
  d["object_id"] = Value::Bin(object_id);
  d["timeout"] = Value::F(timeout_s);
  bool ok = false;
  Value info = Call(raylet_fd_, "client_get_info", d, &ok);
  if (!ok) return std::nullopt;
  const Value* okf = info.find("ok");
  if (okf == nullptr || !okf->as_bool()) {
    const Value* err = info.find("error");
    error_ = err != nullptr && !err->is_nil() ? err->as_str() : "get failed";
    return std::nullopt;
  }
  int64_t size = info.find("size")->as_int();
  std::string data;
  data.reserve(static_cast<size_t>(size));
  const int64_t kChunk = 4 * 1024 * 1024;
  for (int64_t off = 0; off < size; off += kChunk) {
    Value cd = Value::Map();
    cd["object_id"] = Value::Bin(object_id);
    cd["offset"] = Value::I(off);
    cd["size"] = Value::I(std::min(kChunk, size - off));
    Value chunk = Call(raylet_fd_, "fetch_chunk", cd, &ok);
    if (!ok) return std::nullopt;
    data += chunk.find("data")->as_bin();
  }
  if (data.size() < 4) {
    error_ = "object too small to carry a magic";
    return std::nullopt;
  }
  uint32_t magic;
  std::memcpy(&magic, data.data(), 4);
  if (magic != kXlangMagic) {
    error_ = "object is not cross-language (RTX1) encoded";
    return std::nullopt;
  }
  Value out;
  size_t pos = 0;
  if (!Value::unpack(reinterpret_cast<const uint8_t*>(data.data()) + 4,
                     data.size() - 4, &pos, &out)) {
    error_ = "corrupt msgpack payload";
    return std::nullopt;
  }
  return out;
}

Client::TaskResult Client::ParseTaskResult(const Value& r,
                                           double timeout_s) {
  TaskResult result;
  const Value* status = r.find("status");
  if (status == nullptr || status->as_str() != "ok") {
    // Worker errors carry {cls, tb} (make_task_error); raylet errors
    // carry {error}. Surface whichever detail is on the wire.
    const Value* err = r.find("error");
    if (err != nullptr && !err->is_nil()) {
      result.error = err->as_str();
    } else {
      const Value* cls = r.find("cls");
      const Value* tb = r.find("tb");
      std::string msg =
          cls != nullptr && !cls->is_nil() ? cls->as_str() : "task failed";
      if (tb != nullptr && !tb->is_nil()) {
        // Last traceback line holds "Type: message".
        const std::string& t = tb->as_str();
        size_t end = t.find_last_not_of('\n');
        size_t start = end == std::string::npos
                           ? std::string::npos
                           : t.rfind('\n', end);
        if (end != std::string::npos) {
          size_t first = start == std::string::npos ? 0 : start + 1;
          msg = t.substr(first, end - first + 1);
        }
      }
      result.error = msg;
    }
    return result;
  }
  const Value* returns = r.find("returns");
  if (returns == nullptr || returns->as_arr().empty()) {
    result.error = "task returned nothing";
    return result;
  }
  const Value& entry = returns->as_arr()[0];
  const std::string& kind = entry.find("kind")->as_str();
  if (kind == "inline") {
    const std::string& data = entry.find("data")->as_bin();
    uint32_t magic = 0;
    if (data.size() >= 4) std::memcpy(&magic, data.data(), 4);
    if (magic != kXlangMagic) {
      result.error = "result is not cross-language encoded";
      return result;
    }
    size_t pos = 0;
    if (!Value::unpack(reinterpret_cast<const uint8_t*>(data.data()) + 4,
                       data.size() - 4, &pos, &result.value)) {
      result.error = "corrupt result payload";
      return result;
    }
    result.ok = true;
    return result;
  }
  const Value* oid = entry.find("object_id");
  if (oid == nullptr) {
    result.error = "stored result missing object_id";
    return result;
  }
  auto fetched = Get(oid->as_bin(), timeout_s);
  if (!fetched.has_value()) {
    result.error = error_;
    return result;
  }
  result.value = std::move(*fetched);
  result.ok = true;
  return result;
}

Client::ActorInfo Client::GetNamedActor(const std::string& name,
                                        const std::string& ns) {
  ActorInfo info;
  Value d = Value::Map();
  d["name"] = Value::S(name);
  d["namespace"] = Value::S(ns);
  bool ok = false;
  Value r = Call(gcs_fd_, "get_named_actor", d, &ok);
  if (!ok) {
    info.error = error_;
    return info;
  }
  const Value* actor = r.find("actor");
  if (actor == nullptr || actor->is_nil()) {
    info.error = "no such actor: " + name;
    return info;
  }
  const Value* aid = actor->find("actor_id");
  const Value* addr = actor->find("address");
  const Value* port = actor->find("port");
  const Value* state = actor->find("state");
  if (aid == nullptr || addr == nullptr || addr->is_nil() ||
      port == nullptr || port->is_nil()) {
    info.error = "actor " + name + " is not ready (no address yet)";
    return info;
  }
  info.actor_id = aid->as_bin();
  info.address = addr->as_str();
  info.port = port->as_int();
  if (state != nullptr && !state->is_nil()) info.state = state->as_str();
  if (info.state != "ALIVE") {
    // A DEAD/RESTARTING actor's stale address would dial a dead (or
    // recycled) port; report the real condition instead.
    info.error = "actor " + name + " is " +
                 (info.state.empty() ? "not alive" : info.state);
    return info;
  }
  info.ok = true;
  return info;
}

Client::TaskResult Client::ActorCall(const ActorInfo& actor,
                                     const std::string& method,
                                     const std::vector<Value>& args,
                                     double timeout_s) {
  TaskResult result;
  if (!actor.ok) {
    result.error = actor.error.empty() ? "invalid actor handle"
                                       : actor.error;
    return result;
  }
  // One connection per call keeps this client synchronous and simple;
  // latency-sensitive callers can cache the fd themselves.
  std::string err;
  int fd = DialTcp(actor.address, static_cast<int>(actor.port), &err);
  if (fd < 0) {
    result.error = err;
    return result;
  }
  Value d = Value::Map();
  d["actor_id"] = Value::Bin(actor.actor_id);
  d["task_id"] = Value::Bin(RandomId());
  d["method"] = Value::S(method);
  d["plain_args"] = Value::Arr(args);
  d["num_returns"] = Value::I(1);
  d["xlang"] = Value::B(true);
  bool ok = false;
  Value r = Call(fd, "actor_call", d, &ok);
  close(fd);
  if (!ok) {
    result.error = error_;
    return result;
  }
  return ParseTaskResult(r, timeout_s);
}

Client::TaskResult Client::Submit(const std::string& fn_name,
                                  const std::vector<Value>& args,
                                  double timeout_s) {
  TaskResult result;
  Value spec = Value::Map();
  spec["task_id"] = Value::Bin(RandomId());
  spec["job_id"] = Value::Bin(job_id_);
  spec["name"] = Value::S(fn_name);
  spec["fn_name"] = Value::S(fn_name);
  spec["plain_args"] = Value::Arr(args);
  spec["deps"] = Value::Arr();
  spec["num_returns"] = Value::I(1);
  Value res = Value::Map();
  res["CPU"] = Value::F(1.0);
  spec["resources"] = res;
  spec["retriable"] = Value::B(false);

  bool ok = false;
  Value r = Call(raylet_fd_, "submit_task", spec, &ok);
  if (!ok) {
    result.error = error_;
    return result;
  }
  return ParseTaskResult(r, timeout_s);
}

}  // namespace rt
