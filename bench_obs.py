"""Flight-recorder overhead benchmarks. Writes BENCH_OBS.json.

An always-on recorder is only defensible if it is effectively free, so
this bench measures exactly that — the same jitted-compute step loop run
bare vs wrapped in a StepProfiler (full configuration: phase timer,
fence, compile watching, rank-tagged metric emission), plus the cost of
one unified memory sample:

  1. step recorder overhead: a jitted matmul chain calibrated to a few
     ms per call (a small-but-realistic training step: async dispatch,
     GIL released while the device computes, fenced at step end), timed
     per step; arms run interleaved and compared on MEDIANS so OS
     scheduler tails don't masquerade as recorder cost. MIGRATION.md
     pins overhead_pct < 2% from this entry.
  2. recorder cost in isolation: zero-work steps — the absolute
     per-step price (record + ring append + metrics), in microseconds.
  3. journal overhead: the same calibrated step bare vs emitting one
     cluster-black-box journal event per step (util/journal.py), plus
     emit() priced in isolation. MIGRATION.md pins overhead_pct < 2%
     from this entry.
  4. memory accountant: one sample_once() walking a few hundred live
     arrays and publishing the per-device gauges.

Run: python bench_obs.py [--quick]   (--quick: fewer steps, no artifact)
"""

from __future__ import annotations

import json
import statistics
import sys
import time

STEPS = 300
TARGET_WORK_MS = 4.0
ROUNDS = 4
EMPTY_STEPS = 2000
LIVE_ARRAYS = 256


def _make_work(target_ms: float):
    """Calibrate a jitted matmul chain to >= target_ms per call."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((512, 512), dtype=jnp.float32)
    n = 1
    while True:
        g = jax.jit(_matmul_chain, static_argnums=1)  # rtlint: disable=RT002 — fresh wrapper per round generates the retrace events the observatory probe asserts on
        g(x, n).block_until_ready()  # compile  # rtlint: disable=RT001 — warm-up/measured sync is the point of the probe
        t0 = time.perf_counter()
        g(x, n).block_until_ready()  # rtlint: disable=RT001 — measured sync is the point
        dt_ms = (time.perf_counter() - t0) * 1e3
        if dt_ms >= target_ms or n >= 256:
            return g, x, n, dt_ms
        n *= 2


def _matmul_chain(a, n):
    for _ in range(n):
        a = a @ a / 512.0
    return a


def _steps_off(g, x, n, steps):
    out = []
    for _ in range(steps):
        t0 = time.perf_counter()
        g(x, n).block_until_ready()  # rtlint: disable=RT001 — measured sync is the point
        out.append(time.perf_counter() - t0)
    return out


def _steps_on(prof, g, x, n, steps):
    out = []
    for _ in range(steps):
        t0 = time.perf_counter()
        with prof.step(tokens=1024) as s:
            with prof.phase("compute"):
                y = g(x, n)
            s.fence(y)
        out.append(time.perf_counter() - t0)
    return out


def probe_recorder_overhead(results, quick: bool):
    from ray_tpu.train import StepProfiler

    steps = 50 if quick else STEPS
    rounds = 2 if quick else ROUNDS
    g, x, n, work_ms = _make_work(TARGET_WORK_MS)

    prof = StepProfiler(ring=512, rank=0, flops_per_step=n * 2 * 512**3)
    prof.watch_jit(g)
    # Warm both paths, then run the arms INTERLEAVED (off, on, off, on,
    # ...) so load/clock drift lands on both equally.
    _steps_off(g, x, n, 5)
    _steps_on(prof, g, x, n, 5)
    off_ts, on_ts = [], []
    for _ in range(rounds):
        off_ts.extend(_steps_off(g, x, n, steps))
        on_ts.extend(_steps_on(prof, g, x, n, steps))

    off_med = statistics.median(off_ts)
    on_med = statistics.median(on_ts)
    overhead_pct = (on_med - off_med) / off_med * 100.0
    entry = {
        "metric": "step recorder overhead",
        "steps_per_arm": len(off_ts),
        "work_ms_calibrated": round(work_ms, 3),
        "matmul_chain_len": n,
        "off_ms_per_step_p50": round(off_med * 1e3, 4),
        "on_ms_per_step_p50": round(on_med * 1e3, 4),
        "off_ms_per_step_mean": round(statistics.mean(off_ts) * 1e3, 4),
        "on_ms_per_step_mean": round(statistics.mean(on_ts) * 1e3, 4),
        "overhead_pct": round(overhead_pct, 3),
        "recorder_cost_us_per_step": round((on_med - off_med) * 1e6, 2),
    }
    print(json.dumps(entry))
    results.append(entry)

    # Absolute per-step price on empty steps (no work to hide behind).
    m = 200 if quick else EMPTY_STEPS
    t0 = time.perf_counter()
    for _ in range(m):
        with prof.step():
            pass
    bare_us = (time.perf_counter() - t0) / m * 1e6
    entry = {
        "metric": "recorder cost, empty steps",
        "steps": m,
        "cost_us_per_step": round(bare_us, 2),
    }
    print(json.dumps(entry))
    results.append(entry)


def _steps_journal(g, x, n, steps):
    from ray_tpu.util import journal

    out = []
    for i in range(steps):
        t0 = time.perf_counter()
        g(x, n).block_until_ready()  # rtlint: disable=RT001 — measured sync is the point
        journal.emit("train.step", step=i, wall_s=0.005, compiles=0,
                     tokens=1024)
        out.append(time.perf_counter() - t0)
    return out


def probe_journal_overhead(results, quick: bool):
    """Cluster-black-box cost on the train step: the same calibrated
    ~5ms jitted step bare vs emitting one journal event per step (the
    exact record flight_recorder._finish appends). Paired medians over
    interleaved arms; MIGRATION.md pins overhead_pct < 2% from this
    entry. Also prices emit() in isolation (ring append + HLC tick +
    keyed counter), in nanoseconds-scale microseconds."""
    from ray_tpu.util import journal

    steps = 50 if quick else STEPS
    rounds = 2 if quick else ROUNDS
    g, x, n, work_ms = _make_work(TARGET_WORK_MS)

    _steps_off(g, x, n, 5)
    _steps_journal(g, x, n, 5)
    off_ts, on_ts = [], []
    for _ in range(rounds):
        off_ts.extend(_steps_off(g, x, n, steps))
        on_ts.extend(_steps_journal(g, x, n, steps))

    off_med = statistics.median(off_ts)
    on_med = statistics.median(on_ts)
    overhead_pct = (on_med - off_med) / off_med * 100.0
    entry = {
        "metric": "journal overhead",
        "steps_per_arm": len(off_ts),
        "work_ms_calibrated": round(work_ms, 3),
        "off_ms_per_step_p50": round(off_med * 1e3, 4),
        "on_ms_per_step_p50": round(on_med * 1e3, 4),
        "overhead_pct": round(overhead_pct, 3),
        "journal_cost_us_per_step": round((on_med - off_med) * 1e6, 2),
    }
    print(json.dumps(entry))
    results.append(entry)

    # emit() in isolation: the absolute per-event price.
    m = 2000 if quick else 20000
    t0 = time.perf_counter()
    for i in range(m):
        journal.emit("bench.tick", i=i)
    emit_us = (time.perf_counter() - t0) / m * 1e6
    events, dropped = journal.counts()
    entry = {
        "metric": "journal emit cost",
        "emits": m,
        "emit_us": round(emit_us, 3),
        "ring": journal._ring_max,
        "events_total": events,
        "dropped_total": dropped,
    }
    print(json.dumps(entry))
    results.append(entry)


def probe_memory_sample(results, quick: bool):
    import jax.numpy as jnp

    from ray_tpu.util import memory

    n = 32 if quick else LIVE_ARRAYS
    arrays = [jnp.full((64, 64), float(i)) for i in range(n)]
    memory.sample_once()  # warm the gauge registry
    rounds = 3 if quick else 20
    t0 = time.perf_counter()
    for _ in range(rounds):
        sample = memory.sample_once()
    sample_ms = (time.perf_counter() - t0) / rounds * 1e3
    entry = {
        "metric": "memory accountant sample",
        "live_arrays": len(arrays),
        "sample_ms": round(sample_ms, 3),
        "devices": len(sample),
    }
    print(json.dumps(entry))
    results.append(entry)


def main():
    quick = "--quick" in sys.argv
    results = []
    probe_recorder_overhead(results, quick)
    probe_journal_overhead(results, quick)
    probe_memory_sample(results, quick)
    if not quick:
        with open("BENCH_OBS.json", "w") as f:
            json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
