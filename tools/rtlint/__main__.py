"""rtlint CLI.

    python -m tools.rtlint                        lint the default targets
    python -m tools.rtlint ray_tpu/ tools/        lint explicit paths
    python -m tools.rtlint --no-baseline PATH     report every finding
    python -m tools.rtlint --write-baseline       regenerate the baseline
    python -m tools.rtlint --changed              git-diff-scoped pass 2
    python -m tools.rtlint --jobs 8               parallel analysis
    python -m tools.rtlint --format json|sarif    machine-readable output
    python -m tools.rtlint --sarif-out FILE       sarif artifact alongside text
    python -m tools.rtlint --fix                  apply mechanical autofixes
    python -m tools.rtlint --stats                per-rule counts
    python -m tools.rtlint --list-rules           one-line rule catalog
    python -m tools.rtlint --explain RT003        full rule rationale

With no paths, the default target set is linted: ray_tpu/, tools/, and
the root bench_*.py harnesses, resolved against the repo root (the
directory holding tools/rtlint/). Exit codes: 0 clean, 1 new findings
(or stale baseline with --strict-baseline), 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from tools.rtlint.engine import (Baseline, DEFAULT_TARGETS, analyze_paths)
from tools.rtlint.formats import render_json, render_sarif, render_text
from tools.rtlint.rules import ALL_RULES, rule_by_id

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def _changed_files(root: str):
    """Repo-relative .py files touched vs HEAD (staged, unstaged, and
    untracked). Returns None when git itself fails — callers fall back
    to a full pass 2 rather than silently linting nothing."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD", "--", "*.py"],
            capture_output=True, text=True, cwd=root, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard",
             "--", "*.py"],
            capture_output=True, text=True, cwd=root, timeout=30)
        if diff.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    out = set()
    for blob in (diff.stdout, untracked.stdout):
        for line in blob.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                out.add(line)
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rtlint", add_help=True)
    ap.add_argument("paths", nargs="*",
                    help="files or directories to lint (default: "
                         + ", ".join(DEFAULT_TARGETS) + ")")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/rtlint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline file")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="also fail when baselined entries no longer exist "
                         "(debt paid off: refresh the baseline)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--format", default="text",
                    choices=["text", "json", "sarif"], dest="fmt",
                    help="output format (default: text)")
    ap.add_argument("--jobs", "-j", type=int, default=1, metavar="N",
                    help="worker processes for both analysis passes")
    ap.add_argument("--changed", action="store_true",
                    help="restrict findings to files changed vs HEAD "
                         "(+ untracked); the project model still covers "
                         "the full target set")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule finding/suppression/baseline "
                         "counts instead of findings")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the content-hash cache")
    ap.add_argument("--cache", default=None, metavar="FILE",
                    help="cache file (default: <root>/.rtlint_cache.json)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative finding paths "
                         "(default: the checkout containing rtlint)")
    ap.add_argument("--fix", action="store_true",
                    help="apply mechanical autofixes (RT004 leash, "
                         "RT013 boundary tuple-freeze) in place, then "
                         "re-lint")
    ap.add_argument("--sarif-out", default=None, metavar="FILE",
                    help="also write a SARIF artifact of the new "
                         "findings to FILE (independent of --format)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--explain", metavar="RTxxx")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            first = (r.__doc__ or "").strip().splitlines()[0]
            print(f"{r.id}  {r.name:24s} {first}")
        return 0
    if args.explain:
        try:
            r = rule_by_id(args.explain)
        except KeyError:
            print(f"unknown rule {args.explain!r}", file=sys.stderr)
            return 2
        print(f"{r.id} ({r.name})\n")
        print((r.__doc__ or "").strip())
        return 0

    rules = None
    if args.rules:
        try:
            rules = [rule_by_id(x) for x in args.rules.split(",") if x]
        except KeyError as e:
            print(f"unknown rule {e.args[0]!r}", file=sys.stderr)
            return 2
    if args.jobs < 1:
        print("rtlint: --jobs must be >= 1", file=sys.stderr)
        return 2

    # Explicit paths are resolved against the cwd (so `rtlint pkg/`
    # works from anywhere); the default target set is anchored at the
    # repo root regardless of cwd.
    root = os.path.abspath(args.root or REPO_ROOT)
    if args.paths:
        paths = [os.path.abspath(p) for p in args.paths]
    else:
        paths = list(DEFAULT_TARGETS)

    only_files = None
    if args.changed:
        only_files = _changed_files(root)
        if only_files is not None and not only_files:
            print("rtlint: clean (no changed .py files)")
            return 0

    cache_path = None
    if not args.no_cache:
        cache_path = args.cache or os.path.join(root, ".rtlint_cache.json")

    result = analyze_paths(paths, rules=rules, root=root, jobs=args.jobs,
                           cache_path=cache_path, only_files=only_files)
    findings = result.findings

    if args.fix:
        nfixed = _apply_fixes(findings, root)
        if nfixed:
            # Re-lint so the report (and exit code) reflects the
            # post-fix tree; the content-hash cache skips the rest.
            result = analyze_paths(paths, rules=rules, root=root,
                                   jobs=args.jobs, cache_path=cache_path,
                                   only_files=only_files)
            findings = result.findings

    if args.write_baseline:
        bl = Baseline.from_findings(findings)
        bl.save(args.baseline)
        by_rule = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items()))
        print(f"wrote {len(findings)} findings to {args.baseline} "
              f"({summary or 'clean'})")
        return 0

    baseline = (Baseline() if args.no_baseline
                else Baseline.load(args.baseline))
    new = baseline.new_findings(findings)
    stale = [] if args.no_baseline else baseline.stale_entries(findings)

    if args.stats:
        _print_stats(findings, new, result.suppressed, baseline,
                     rules or ALL_RULES)
        return 1 if new else 0

    nrules = len(ALL_RULES) if rules is None else len(rules)
    meta = dict(total=len(findings), files=result.files, rules=nrules,
                baselined_absorbed=len(findings) - len(new), stale=stale)
    if args.sarif_out:
        docs = {r.id: (r.__doc__ or "").strip() for r in ALL_RULES}
        docs["RT000"] = "analyzer degradation note"
        with open(args.sarif_out, "w", encoding="utf-8") as fh:
            fh.write(render_sarif(new, rule_docs=docs))
    if args.fmt == "json":
        print(render_json(new, suppressed=result.suppressed, **meta))
    elif args.fmt == "sarif":
        docs = {r.id: (r.__doc__ or "").strip() for r in ALL_RULES}
        docs["RT000"] = "analyzer degradation note"
        print(render_sarif(new, rule_docs=docs))
    else:
        print(render_text(new, **meta))
    if new:
        return 1
    return 1 if (stale and args.strict_baseline) else 0


def _apply_fixes(findings, root: str) -> int:
    """Rewrite files for fixable findings; returns files changed.

    Driven by the analyzer's (suppression-filtered) findings rather
    than a raw re-scan, so `# rtlint:` suppressed sites — e.g. an
    intentional fire-and-forget — are never touched.
    """
    from tools.rtlint.fix import FIXABLE_RULES, fix_source
    by_path = {}
    for f in findings:
        if f.rule in FIXABLE_RULES:
            by_path.setdefault(f.path, {}).setdefault(
                f.rule, set()).add(f.line)
    changed = 0
    for rel, rule_lines in sorted(by_path.items()):
        abspath = os.path.join(root, rel.replace("/", os.sep))
        try:
            with open(abspath, "r", encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            print(f"rtlint: --fix cannot read {rel}: {e}",
                  file=sys.stderr)
            continue
        out, notes = fix_source(
            src, rel,
            rt004_lines=rule_lines.get("RT004", set()),
            rt013_lines=rule_lines.get("RT013", set()))
        for note in notes:
            print(f"rtlint: fix: {note}")
        if out != src:
            with open(abspath, "w", encoding="utf-8") as fh:
                fh.write(out)
            changed += 1
    if changed:
        print(f"rtlint: --fix rewrote {changed} file(s)")
    return changed


def _print_stats(findings, new, suppressed, baseline, rules):
    by_rule = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    base_by_rule = {}
    for fp, n in baseline.counts.items():
        rid = fp.split("|", 1)[0]
        base_by_rule[rid] = base_by_rule.get(rid, 0) + n
    new_by_rule = {}
    for f in new:
        new_by_rule[f.rule] = new_by_rule.get(f.rule, 0) + 1
    ids = sorted({r.id for r in rules} | set(by_rule) | set(base_by_rule)
                 | set(suppressed))
    print(f"{'rule':8s} {'found':>6s} {'new':>6s} {'baseline':>9s} "
          f"{'suppressed':>11s}")
    for rid in ids:
        print(f"{rid:8s} {by_rule.get(rid, 0):6d} "
              f"{new_by_rule.get(rid, 0):6d} "
              f"{base_by_rule.get(rid, 0):9d} "
              f"{suppressed.get(rid, 0):11d}")
    tot = (sum(by_rule.values()), sum(new_by_rule.values()),
           sum(base_by_rule.values()), sum(suppressed.values()))
    print(f"{'total':8s} {tot[0]:6d} {tot[1]:6d} {tot[2]:9d} "
          f"{tot[3]:11d}")


if __name__ == "__main__":
    sys.exit(main())
