"""rtlint CLI.

    python -m tools.rtlint ray_tpu/              lint against the baseline
    python -m tools.rtlint --no-baseline PATH    report every finding
    python -m tools.rtlint --write-baseline PATH regenerate the baseline
    python -m tools.rtlint --list-rules          one-line rule catalog
    python -m tools.rtlint --explain RT003       full rule rationale

Exit codes: 0 clean, 1 new findings (or stale-baseline with --strict-
baseline), 2 usage error.
"""

from __future__ import annotations

import argparse
import collections
import os
import sys

from tools.rtlint.engine import Baseline, lint_paths
from tools.rtlint.rules import ALL_RULES, rule_by_id

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rtlint", add_help=True)
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/rtlint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline file")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="also fail when baselined entries no longer exist "
                         "(debt paid off: refresh the baseline)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--explain", metavar="RTxxx")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            first = (r.__doc__ or "").strip().splitlines()[0]
            print(f"{r.id}  {r.name:24s} {first}")
        return 0
    if args.explain:
        try:
            r = rule_by_id(args.explain)
        except KeyError:
            print(f"unknown rule {args.explain!r}", file=sys.stderr)
            return 2
        print(f"{r.id} ({r.name})\n")
        print((r.__doc__ or "").strip())
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        print("rtlint: no paths given", file=sys.stderr)
        return 2

    rules = None
    if args.rules:
        try:
            rules = [rule_by_id(x) for x in args.rules.split(",") if x]
        except KeyError as e:
            print(f"unknown rule {e.args[0]!r}", file=sys.stderr)
            return 2

    findings = lint_paths(args.paths, rules)

    if args.write_baseline:
        Baseline.from_findings(findings).save(args.baseline)
        by_rule = collections.Counter(f.rule for f in findings)
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items()))
        print(f"wrote {len(findings)} findings to {args.baseline} "
              f"({summary or 'clean'})")
        return 0

    baseline = (Baseline() if args.no_baseline
                else Baseline.load(args.baseline))
    new = baseline.new_findings(findings)
    for f in new:
        print(f)
    stale = [] if args.no_baseline else baseline.stale_entries(findings)
    if stale and (args.strict_baseline or not new):
        print(f"note: {len(stale)} baselined finding(s) no longer exist — "
              f"debt paid; refresh with --write-baseline", file=sys.stderr)
    if new:
        by_rule = collections.Counter(f.rule for f in new)
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items()))
        print(f"rtlint: {len(new)} new finding(s) [{summary}] "
              f"({len(findings) - len(new)} baselined/suppressed absorbed)",
              file=sys.stderr)
        return 1
    print(f"rtlint: clean ({len(findings)} baselined finding(s), "
          f"{len(ALL_RULES) if rules is None else len(rules)} rules)")
    return 1 if (stale and args.strict_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
