"""rtlint v3: the linear-resource catalog.

Each :class:`ResourceSpec` teaches the lifecycle engine (rules RT014/
RT015/RT016) one acquire/release protocol from the runtime, each
encoding a bug class this repo actually shipped:

- ``pages``   — PagePool pages: ``alloc``/``ref`` ↔ ``release`` with
  all-or-nothing rollback (the PR 11 PagePool leak class),
- ``bundles`` — placement-group bundles: ``reserve*`` ↔ ``release*``/
  ``cancel_bundle``, double-release = the PR 10 double-credit bug,
- ``fence``   — GCS fences / resize obligations: ``arm*`` ↔ ``lift*``
  on every claimant exit path (the PR 14 obligation protocol),
- ``ref``     — ObjectRefs: ``.remote()`` results that must be awaited,
  gotten, or stored (the RT004 class, now path-sensitive),
- ``lock``    — explicit ``.acquire()`` without ``.release()`` on some
  path (``with`` blocks release structurally and are exempt).

Recognition is (method leaf name, receiver-name hint) so `pool.alloc`
matches and `mmap.alloc` does not. Release recognition accepts the
tracked value as an argument, as an element of an iterated release
(``for p in pages: pool.release([p])``), or — via the interprocedural
summaries — as an argument to a helper known to release that kind.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import FrozenSet, Tuple

# Receiver-name hints: substring match on the lowercase receiver leaf
# ("self._pool" -> "_pool"). Empty = any receiver.
POOL_HINTS = ("pool", "pages", "pagepool", "kv")
BUNDLE_HINTS = ()       # module-level functions; leaf names are unique
FENCE_HINTS = ()
LOCK_HINTS = ("lock", "mutex", "sem", "cond")


@dataclass(frozen=True)
class ResourceSpec:
    kind: str
    rule: str
    noun: str                       # human name used in messages
    # Value-binding acquires: `x = recv.leaf(...)` makes x held.
    acquire_value: FrozenSet[str] = frozenset()
    acquire_hints: Tuple[str, ...] = ()
    # Receivers that do NOT acquire despite the leaf name matching
    # (`rt.remote(cls)` wraps a class; `Actor.remote()` builds a
    # handle, not an ObjectRef). When set, the receiver must also be
    # non-empty and lowercase (a capitalized receiver is a class).
    acquire_recv_deny: Tuple[str, ...] = ()
    # Argument-obligation acquires: `recv.leaf(x)` makes x held
    # (incref/ref/arm: the protocol owes a matching release on x).
    acquire_arg: FrozenSet[str] = frozenset()
    # Release leaves: `recv.leaf(x)` / `leaf(x)` releases x.
    release: FrozenSet[str] = frozenset()
    release_hints: Tuple[str, ...] = ()
    # Consumers: like releases but also fire when the value is the
    # *receiver* (`ref.cancel()`) or awaited (`await ref`).
    consume: FrozenSet[str] = frozenset()
    double_release: bool = False
    # Whether passing the acquired token to another call transfers the
    # obligation (incref'd pages handed to their table: yes; fence
    # tokens are plain ids passed around freely: no).
    escape_transfers: bool = True
    # Whether an uncaught exception edge counts as a leak for this kind
    # (pages/bundles/fences: yes — that IS the shipped bug shape; refs:
    # no, a propagating error usually abandons the whole call anyway).
    leak_on_raise: bool = True
    advice: str = ""


PAGES = ResourceSpec(
    kind="pages", rule="RT014", noun="PagePool pages",
    acquire_value=frozenset({"alloc"}),
    acquire_hints=POOL_HINTS,
    acquire_arg=frozenset({"ref", "incref"}),
    release=frozenset({"release", "free", "decref", "evict_pages"}),
    release_hints=POOL_HINTS + ("cache", "prefix"),
    double_release=True,
    advice=("wrap the post-alloc steps in try/except and release on "
            "the error path (all-or-nothing rollback), or hand the "
            "pages to their owning table before anything can raise"),
)

BUNDLES = ResourceSpec(
    kind="bundles", rule="RT015", noun="placement-group bundles",
    acquire_value=frozenset({"reserve_placement_group_bundles",
                             "reserve_pg_bundles", "reserve_bundles"}),
    release=frozenset({"release_placement_group_bundles",
                       "release_pg_bundles", "release_bundles",
                       "cancel_bundle", "remove_placement_group"}),
    double_release=True,
    advice=("release reserved bundles exactly once per exit path — "
            "the PR 10 cancel_bundle double-credit corrupted node "
            "accounting by crediting bundle AND node"),
)

FENCES = ResourceSpec(
    kind="fence", rule="RT015", noun="fence/resize obligation",
    acquire_arg=frozenset({"arm_fence", "arm_obligation",
                           "arm_resize_obligation", "register_fence"}),
    release=frozenset({"lift_fence", "lift_obligation",
                       "lift_resize_obligations", "release_fence",
                       "unfence"}),
    double_release=False,
    escape_transfers=False,
    advice=("every claimant exit path (including exception edges) must "
            "lift the obligation it armed, or reservations wedge "
            "forever (PR 14 resize-obligation protocol)"),
)

REFS = ResourceSpec(
    kind="ref", rule="RT016", noun="ObjectRef",
    acquire_value=frozenset({"remote"}),
    acquire_recv_deny=("rt", "ray"),
    release=frozenset({"get", "wait", "cancel", "prefetch"}),
    release_hints=("rt", "ray"),
    consume=frozenset({"result", "cancel"}),
    double_release=False,
    leak_on_raise=False,
    advice=("await/get the ref, store it somewhere it will be reaped, "
            "or pass it to rt.get/rt.wait — a dropped ref silently "
            "discards the task's error and pins its result in the "
            "object store until GC"),
)

LOCKS = ResourceSpec(
    kind="lock", rule="RT016", noun="lock",
    acquire_arg=frozenset(),
    acquire_value=frozenset(),
    # populated dynamically: `recv.acquire()` with a lock-ish receiver
    # tracks the receiver itself; see lifecycle.py.
    release=frozenset({"release"}),
    release_hints=LOCK_HINTS,
    double_release=False,
    advice=("prefer `with lock:` — an explicit acquire() must be "
            "released on every exit path including exceptions"),
)

ALL_SPECS = (PAGES, BUNDLES, FENCES, REFS, LOCKS)


def receiver_matches(leaf_receiver: str, hints: Tuple[str, ...]) -> bool:
    if not hints:
        return True
    low = leaf_receiver.lower()
    return any(h in low for h in hints)


def acquire_receiver_ok(spec: ResourceSpec, leaf_receiver: str) -> bool:
    """Receiver check for value-binding acquires, honoring the spec's
    deny list (class constructors and module-level wrappers that share
    the acquire leaf name but return a different thing)."""
    if not receiver_matches(leaf_receiver, spec.acquire_hints):
        return False
    if spec.acquire_recv_deny:
        if not leaf_receiver or leaf_receiver in spec.acquire_recv_deny:
            return False
        if leaf_receiver.lstrip("_")[:1].isupper():
            return False
    return True
