"""rtlint v3: per-function control-flow graphs.

``build_cfg(func_def)`` lowers one ``def``/``async def`` body to a
statement-level CFG: one node per simple statement (plus entry/exit
markers), edges for branches, loop back-edges, ``break``/``continue``,
early ``return``, and — the part the lifecycle rules live on —
*exception edges*. Any statement that can raise (an explicit ``raise``,
an ``assert``, or any statement containing a call) gets an edge to the
innermost enclosing ``except``/``finally`` construct, or to the
function's ``raise_exit`` when nothing encloses it. ``finally`` bodies
are duplicated (a normal-completion copy and an exceptional copy that
keeps propagating afterwards) so a path through ``finally`` reads
correctly in both directions. ``with contextlib.suppress(...)`` routes
body exceptions to the statement *after* the with, modelling the
swallow.

The graph is deliberately statement-grained rather than basic-block
grained: findings report the exact line sequence of the leaking path,
and statements are the natural unit for that.

Nodes are integers; ``CFG.stmts[i]`` is the AST statement (None for the
entry/exit markers), ``CFG.succ[i]`` the outgoing ``(target, label)``
edges with label in {"next", "true", "false", "loop", "exc", "raise"}.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

# Calls to these bare names are assumed non-raising for exception-edge
# purposes: flagging "len() might raise" would drown every real leak.
SAFE_CALLS = {
    "len", "min", "max", "abs", "sum", "int", "float", "str", "bool",
    "bytes", "list", "dict", "set", "tuple", "frozenset", "sorted",
    "reversed", "enumerate", "zip", "range", "repr", "id", "type",
    "isinstance", "issubclass", "getattr", "hasattr", "format", "print",
    "iter", "next", "round", "divmod", "hash", "callable", "vars",
}
# Method leaves assumed non-raising (container plumbing).
SAFE_METHODS = {
    "append", "extend", "add", "discard", "update", "setdefault",
    "keys", "values", "items", "get", "pop", "popleft", "clear",
    "copy", "join", "split", "strip", "startswith", "endswith",
    "lower", "upper", "format", "encode", "decode", "count", "index",
    "debug", "info", "warning", "error", "exception", "critical",
    "monotonic", "time", "perf_counter", "sleep", "suppress",
}


class CFG:
    ENTRY = 0

    def __init__(self, func: ast.AST):
        self.func = func
        self.stmts: List[Optional[ast.stmt]] = [None]  # 0 = entry
        self.kinds: List[str] = ["entry"]
        self.succ: Dict[int, List[Tuple[int, str]]] = {0: []}
        # Synthetic exits, created lazily via _marker().
        self.exit = self._marker("exit")          # return / fall-off-end
        self.raise_exit = self._marker("raise")   # uncaught exception

    def _marker(self, kind: str) -> int:
        idx = len(self.stmts)
        self.stmts.append(None)
        self.kinds.append(kind)
        self.succ[idx] = []
        return idx

    def add(self, stmt: ast.stmt, kind: str = "stmt") -> int:
        idx = len(self.stmts)
        self.stmts.append(stmt)
        self.kinds.append(kind)
        self.succ[idx] = []
        return idx

    def edge(self, src: int, dst: int, label: str = "next"):
        if (dst, label) not in self.succ[src]:
            self.succ[src].append((dst, label))

    def line(self, idx: int) -> int:
        stmt = self.stmts[idx]
        return getattr(stmt, "lineno", 0) if stmt is not None else 0

    def is_exit(self, idx: int) -> bool:
        return idx in (self.exit, self.raise_exit)


def _expr_may_raise(*nodes: ast.AST) -> bool:
    for root in nodes:
        if root is None:
            continue
        for node in ast.walk(root):
            if isinstance(node, (ast.Await, ast.YieldFrom)):
                return True
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in SAFE_CALLS:
                continue
            if isinstance(func, ast.Attribute) \
                    and func.attr in SAFE_METHODS:
                continue
            return True
    return False


def may_raise(stmt: ast.stmt) -> bool:
    """Can executing this statement's *own* evaluation raise (not its
    nested body, for compound statements)? Conservative-but-calibrated:
    explicit raise/assert always; otherwise any embedded call whose
    target is not on the safe list. Attribute/subscript access alone is
    not counted — counting it flags every line of real code."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    if isinstance(stmt, ast.If):
        return _expr_may_raise(stmt.test)
    if isinstance(stmt, ast.While):
        return _expr_may_raise(stmt.test)
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return _expr_may_raise(stmt.iter)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return _expr_may_raise(*[i.context_expr for i in stmt.items])
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Try)):
        return False
    return _expr_may_raise(stmt)


def _is_suppress_with(stmt: ast.stmt) -> bool:
    if not isinstance(stmt, (ast.With, ast.AsyncWith)):
        return False
    for item in stmt.items:
        expr = item.context_expr
        target = expr.func if isinstance(expr, ast.Call) else expr
        leaf = None
        if isinstance(target, ast.Attribute):
            leaf = target.attr
        elif isinstance(target, ast.Name):
            leaf = target.id
        if leaf == "suppress":
            return True
    return False


class _Builder:
    """Recursive statement-list lowering.

    ``exc_targets`` is a stack; each entry is a list of node ids that a
    raised exception inside the region flows to (handler heads and/or
    the exceptional finally copy). An empty stack means exceptions leave
    the function via ``raise_exit``.
    """

    def __init__(self, cfg: CFG):
        self.cfg = cfg
        self.exc_targets: List[List[int]] = []
        # (continue_target, break_sinks) per enclosing loop. break_sinks
        # is a mutable list the loop collects exits from.
        self.loops: List[Tuple[int, List[int]]] = []
        # Statements that leave the function normally (return) route
        # through enclosing finally blocks; each frame is the id of the
        # normal-copy finally head to pass through, or None.
        self.finally_heads: List[Optional[int]] = []

    # -- exception plumbing ----------------------------------------------
    def _raise_edges(self, idx: int):
        if self.exc_targets and self.exc_targets[-1]:
            for tgt in self.exc_targets[-1]:
                self.cfg.edge(idx, tgt, "exc")
        else:
            self.cfg.edge(idx, self.cfg.raise_exit, "exc")

    def _route_return(self, idx: int):
        """A return passes through enclosing finally bodies (innermost
        first) via their dedicated return-path copies; with none, it
        reaches the function exit directly."""
        for head in reversed(self.finally_heads):
            if head is not None:
                self.cfg.edge(idx, head, "next")
                return  # the return-copy's tail continues the routing
        self.cfg.edge(idx, self.cfg.exit, "next")

    # -- main lowering ----------------------------------------------------
    def build(self, body: List[ast.stmt], frontier: List[int],
              ) -> List[int]:
        """Lower `body`; `frontier` is the set of nodes whose fall-
        through enters the list. Returns the new frontier (nodes that
        fall through past the end of the list)."""
        for stmt in body:
            if not frontier:
                break  # unreachable code after return/raise/break
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _join(self, frontier: List[int], idx: int, label: str = "next"):
        for f in frontier:
            self.cfg.edge(f, idx, label)

    def _stmt(self, stmt: ast.stmt, frontier: List[int]) -> List[int]:
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            idx = cfg.add(stmt, "branch")
            self._join(frontier, idx)
            if may_raise(stmt):
                self._raise_edges(idx)
            then = self.build(stmt.body, [idx])
            els = self.build(stmt.orelse, [idx]) if stmt.orelse else [idx]
            return then + els

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = cfg.add(stmt, "loop")
            self._join(frontier, head)
            if may_raise(stmt):
                self._raise_edges(head)
            breaks: List[int] = []
            self.loops.append((head, breaks))
            tail = self.build(stmt.body, [head])
            self.loops.pop()
            self._join(tail, head, "loop")
            out = self.build(stmt.orelse, [head]) if stmt.orelse else [head]
            return out + breaks

        if isinstance(stmt, ast.Try):
            return self._try(stmt, frontier)

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            idx = cfg.add(stmt, "with")
            self._join(frontier, idx)
            if may_raise(stmt):
                self._raise_edges(idx)
            if _is_suppress_with(stmt):
                # Body exceptions are swallowed by __exit__ and control
                # resumes after the with block: route them to a
                # synthetic join node that becomes part of the frontier.
                join = cfg._marker("suppress-join")
                self.exc_targets.append([join])
                tail = self.build(stmt.body, [idx])
                self.exc_targets.pop()
                return tail + [join]
            tail = self.build(stmt.body, [idx])
            return tail

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            idx = cfg.add(stmt, "def")   # nested defs: opaque statement
            self._join(frontier, idx)
            return [idx]

        if isinstance(stmt, ast.Return):
            idx = cfg.add(stmt, "return")
            self._join(frontier, idx)
            if may_raise(stmt):
                self._raise_edges(idx)
            self._route_return(idx)
            return []

        if isinstance(stmt, ast.Raise):
            idx = cfg.add(stmt, "raise")
            self._join(frontier, idx)
            self._raise_edges(idx)
            return []

        if isinstance(stmt, ast.Break):
            idx = cfg.add(stmt, "break")
            self._join(frontier, idx)
            if self.loops:
                self.loops[-1][1].append(idx)
            return []

        if isinstance(stmt, ast.Continue):
            idx = cfg.add(stmt, "continue")
            self._join(frontier, idx)
            if self.loops:
                cfg.edge(idx, self.loops[-1][0], "loop")
            return []

        # Simple statement (Assign, Expr, AugAssign, Assert, ...).
        idx = cfg.add(stmt)
        self._join(frontier, idx)
        if may_raise(stmt):
            self._raise_edges(idx)
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, (ast.Yield, ast.YieldFrom)):
            # A generator can be closed at any yield: GeneratorExit
            # leaves the function through finally/raise machinery.
            self._raise_edges(idx)
        return [idx]

    # -- try/except/finally ------------------------------------------------
    def _try(self, stmt: ast.Try, frontier: List[int]) -> List[int]:
        cfg = self.cfg
        out: List[int] = []

        # Exceptional finally copy first, so handler-less raises and
        # handler-internal raises have somewhere to land.
        exc_finally_head: Optional[int] = None
        exc_finally_tail: List[int] = []
        if stmt.finalbody:
            marker = cfg.add(stmt, "finally")
            exc_finally_head = marker
            saved_loops, self.loops = self.loops, []
            exc_finally_tail = self.build(stmt.finalbody, [marker])
            self.loops = saved_loops
            # After the exceptional copy, the exception keeps going.
            for t in exc_finally_tail:
                if self.exc_targets and self.exc_targets[-1]:
                    for tgt in self.exc_targets[-1]:
                        cfg.edge(t, tgt, "exc")
                else:
                    cfg.edge(t, cfg.raise_exit, "exc")

        # Handler heads: body exceptions dispatch to every handler (we
        # do not model type matching) and, with no handler, straight to
        # the exceptional finally / outward.
        handler_heads: List[int] = []
        handler_nodes: List[Tuple[int, ast.ExceptHandler]] = []
        for handler in stmt.handlers:
            h = cfg.add(handler, "except")
            handler_heads.append(h)
            handler_nodes.append((h, handler))
        body_exc: List[int] = list(handler_heads)
        if not body_exc and exc_finally_head is not None:
            body_exc = [exc_finally_head]
        # A raise that no local handler matches still escapes: when
        # handlers exist AND a finally exists, the finally is also a
        # target (unmatched-type path).
        if handler_heads and exc_finally_head is not None:
            body_exc.append(exc_finally_head)

        self.exc_targets.append(body_exc)
        if stmt.finalbody:
            # returns inside the body route through a dedicated
            # return-path copy of the finally (built after the body)
            # whose tail keeps unwinding — NOT through the fall-through
            # copy, which would wrongly rejoin the post-try code.
            return_head: Optional[int] = cfg._marker("finally")
        else:
            return_head = None
        self.finally_heads.append(return_head)

        body_tail = self.build(stmt.body, frontier)
        body_tail = self.build(stmt.orelse, body_tail) \
            if stmt.orelse else body_tail
        self.exc_targets.pop()

        # Handlers run with the *outer* exception context (a raise in a
        # handler propagates out, or into the exceptional finally); a
        # return in a handler still unwinds through this finally, so
        # the finally frame stays pushed.
        handler_tails: List[int] = []
        for h, handler in handler_nodes:
            targets = ([exc_finally_head] if exc_finally_head is not None
                       else list(self.exc_targets[-1])
                       if self.exc_targets else [])
            self.exc_targets.append(targets)
            tail = self.build(handler.body, [h])
            self.exc_targets.pop()
            handler_tails.extend(tail)
        self.finally_heads.pop()

        if return_head is not None:
            saved_loops, self.loops = self.loops, []
            ret_tail = self.build(stmt.finalbody, [return_head])
            self.loops = saved_loops
            for t in ret_tail:
                self._route_return(t)

        # Normal finally copy: body + handler fall-throughs pass
        # through it, then continue after the try.
        if stmt.finalbody:
            normal_head = cfg.add(stmt, "finally")
            self._join(body_tail + handler_tails, normal_head)
            saved_loops, self.loops = self.loops, []
            tail = self.build(stmt.finalbody, [normal_head])
            self.loops = saved_loops
            out.extend(tail)
        else:
            out.extend(body_tail + handler_tails)
        return out


def build_cfg(func: ast.AST) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef body."""
    cfg = CFG(func)
    b = _Builder(cfg)
    tail = b.build(list(getattr(func, "body", [])), [CFG.ENTRY])
    for t in tail:
        cfg.edge(t, cfg.exit, "next")
    return cfg


def iter_paths(cfg: CFG, start: int = CFG.ENTRY, max_states: int = 20000):
    """Debug/test helper: DFS enumeration of (node sequence) paths from
    `start` to either exit, with a visited-state bound. Used by the CFG
    unit tests; the lifecycle analysis does its own stateful walk."""
    paths = []
    stack = [(start, [start])]
    steps = 0
    seen = set()
    while stack and steps < max_states:
        steps += 1
        node, path = stack.pop()
        if cfg.is_exit(node):
            paths.append(path)
            continue
        for dst, _label in cfg.succ.get(node, ()):
            if (node, dst) in zip(path, path[1:]):
                continue  # do not retake the same edge within one path
            key = (dst, tuple(path[-3:]))
            if key in seen and len(path) > 64:
                continue
            seen.add(key)
            stack.append((dst, path + [dst]))
    return paths
