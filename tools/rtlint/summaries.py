"""rtlint v3: interprocedural acquire/release summaries.

The lifecycle rules (RT014–RT016) walk one function's CFG at a time;
this pass gives that walk eyes across call boundaries. Over the
existing :class:`~tools.rtlint.project.ProjectModel` call graph it
computes, per function id, two facts per resource kind:

- ``releases``      — calling this function (with the resource as an
  argument, or on ``self``) releases the resource: it calls a release
  leaf for that kind directly, or calls a helper that does. Lets
  ``self._cleanup(pages)`` count as the release instead of a leak.
- ``returns_fresh`` — this function may *return* a freshly acquired
  resource (its ``ret_calls`` reach an acquire leaf or a helper that
  returns fresh). Lets ``pages = self._grab_pages(n)`` start tracking
  even though ``alloc`` happened two frames down.

Both are may-analyses propagated to a fixed point over the call
graph, so helper-mediated protocols are understood without any
per-function annotation.
"""

from __future__ import annotations

from typing import Dict, Set

from .resources import ALL_SPECS, acquire_receiver_ok, receiver_matches


class LifecycleSummaries:
    """Per-function release / returns-fresh facts over a ProjectModel."""

    def __init__(self, model):
        self.model = model
        # fid -> set of kinds
        self.releases: Dict[str, Set[str]] = {}
        self.returns_fresh: Dict[str, Set[str]] = {}
        if model is not None:
            self._compute()

    # -- queries ----------------------------------------------------------
    def call_releases(self, summary: Dict, fn: Dict,
                      dotted: str) -> Set[str]:
        """Kinds released by the call-site `dotted` written inside `fn`,
        via project-local resolution. Empty set when unresolvable."""
        if self.model is None:
            return set()
        fid = self.model.resolve_call(summary, fn, dotted)
        if not fid or fid.startswith("<module>::"):
            return set()
        return self.releases.get(fid, set())

    def call_returns_fresh(self, summary: Dict, fn: Dict,
                           dotted: str) -> Set[str]:
        """Kinds freshly acquired by the value returned from the
        call-site `dotted` written inside `fn`."""
        if self.model is None:
            return set()
        fid = self.model.resolve_call(summary, fn, dotted)
        if not fid or fid.startswith("<module>::"):
            return set()
        return self.returns_fresh.get(fid, set())

    # -- computation ------------------------------------------------------
    def _compute(self):
        model = self.model
        # Seed with direct facts from each function's summarized calls.
        for fid, summary, fn in model._all_funcs():
            rel: Set[str] = set()
            for dotted, _lineno in fn.get("calls", ()):
                leaf = dotted.split(".")[-1]
                recv = dotted.split(".")[-2] if "." in dotted else ""
                for spec in ALL_SPECS:
                    if leaf in spec.release and receiver_matches(
                            recv, spec.release_hints):
                        rel.add(spec.kind)
            if rel:
                self.releases[fid] = rel

            fresh: Set[str] = set()
            for dotted in fn.get("ret_calls", ()):
                parts = [p.replace("()", "") for p in dotted.split(".")]
                leaf = parts[-1]
                recv = parts[-2] if len(parts) > 1 else ""
                for spec in ALL_SPECS:
                    if leaf not in spec.acquire_value:
                        continue
                    if not acquire_receiver_ok(spec, recv):
                        continue
                    # A capitalized segment anywhere in the chain means
                    # a class constructor (`Cls.options().remote()` is
                    # a handle, not a fresh resource).
                    if spec.acquire_recv_deny and any(
                            p.lstrip("_")[:1].isupper()
                            for p in parts[:-1]):
                        continue
                    fresh.add(spec.kind)
            if fresh:
                self.returns_fresh[fid] = fresh

        # Fixed point: releases flow caller-ward along call edges;
        # returns-fresh flows along *returned* calls only.
        changed = True
        while changed:
            changed = False
            for caller, callees in model.edges.items():
                have = self.releases.setdefault(caller, set())
                before = len(have)
                for c in callees:
                    have |= self.releases.get(c, set())
                if len(have) != before:
                    changed = True
        # Drop empty entries so .get(fid, set()) stays cheap to reason
        # about in tests.
        self.releases = {k: v for k, v in self.releases.items() if v}

        changed = True
        while changed:
            changed = False
            for fid, summary, fn in model._all_funcs():
                ret_calls = fn.get("ret_calls", ())
                if not ret_calls:
                    continue
                have = self.returns_fresh.setdefault(fid, set())
                before = len(have)
                for dotted in ret_calls:
                    callee = model.resolve_call(summary, fn, dotted)
                    if callee and not callee.startswith("<module>::"):
                        have |= self.returns_fresh.get(callee, set())
                if len(have) != before:
                    changed = True
        self.returns_fresh = {
            k: v for k, v in self.returns_fresh.items() if v}


def build_summaries(model) -> LifecycleSummaries:
    return LifecycleSummaries(model)
