"""Output renderers for rtlint: text (default), json, sarif.

``json`` is the machine interface for bots and the bench harness;
``sarif`` (2.1.0) is what code-review UIs ingest. Both render the same
post-baseline view the text output shows: the findings that would fail
the gate, plus run metadata. Renderers are pure — they return a string
and never exit — so the CLI owns all exit-code policy.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from tools.rtlint.engine import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def render_text(new: Sequence[Finding], *, total: int, files: int,
                rules: int, baselined_absorbed: int,
                stale: Sequence[str] = ()) -> str:
    lines = [str(f) for f in new]
    if new:
        by_rule: Dict[str, int] = {}
        for f in new:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items()))
        lines.append(f"rtlint: {len(new)} new finding(s) [{summary}] "
                     f"({baselined_absorbed} baselined/suppressed "
                     f"absorbed)")
    else:
        lines.append(f"rtlint: clean ({baselined_absorbed} baselined "
                     f"finding(s), {rules} rules, {files} files)")
    if stale:
        lines.append(f"note: {len(stale)} baselined finding(s) no longer "
                     f"exist — debt paid; refresh with --write-baseline")
    return "\n".join(lines)


def render_json(new: Sequence[Finding], *, total: int, files: int,
                rules: int, baselined_absorbed: int,
                suppressed: Optional[Dict[str, int]] = None,
                stale: Sequence[str] = ()) -> str:
    payload = {
        "tool": "rtlint",
        "files": files,
        "rules": rules,
        "total_findings": total,
        "baselined_absorbed": baselined_absorbed,
        "suppressed": dict(sorted((suppressed or {}).items())),
        "stale_baseline_entries": list(stale),
        "new_findings": [f.to_dict() for f in new],
    }
    return json.dumps(payload, indent=1)


def render_sarif(new: Sequence[Finding], *, rule_docs: Dict[str, str],
                 **_meta) -> str:
    """SARIF 2.1.0 with one rule descriptor per rule that fired.

    RT000 (analyzer degradation notes) are emitted at level "note";
    everything else is "warning" — rtlint findings gate on the baseline,
    not on severity.
    """
    fired = sorted({f.rule for f in new})
    rules = [{
        "id": rid,
        "shortDescription": {
            "text": (rule_docs.get(rid) or rid).splitlines()[0]},
    } for rid in fired]
    index = {rid: i for i, rid in enumerate(fired)}
    results = [{
        "ruleId": f.rule,
        "ruleIndex": index[f.rule],
        "level": "note" if f.rule == "RT000" else "warning",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(f.line, 1),
                           "startColumn": max(f.col, 0) + 1},
            },
            "logicalLocations": [{"fullyQualifiedName": f.scope}],
        }],
        "partialFingerprints": {"rtlint/v1": f.fingerprint},
    } for f in new]
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "rtlint",
                "informationUri":
                    "tools/rtlint/RULES.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=1)
