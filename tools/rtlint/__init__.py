"""rtlint: runtime-aware static analysis for the ray_tpu codebase.

The bug classes the first performance/robustness PRs fixed — silent jit
retraces, per-step host syncs, unbounded actor-side gets, unfenced DCN
collectives, exception swallowing in the control plane — are all
*statically detectable*. This package turns them into pre-merge
diagnostics: an AST-based rule engine (stdlib ``ast``, zero deps) with
inline suppressions and a committed baseline so existing debt is
tracked without blocking CI.

Usage:
    python -m tools.rtlint ray_tpu/                 # lint against baseline
    python -m tools.rtlint --list-rules             # rule catalog
    python -m tools.rtlint --write-baseline ray_tpu/  # re-baseline

Rules are documented in tools/rtlint/RULES.md and in each rule's
docstring (``--explain RTxxx`` prints it). Suppress a finding inline
with ``# rtlint: disable=RT001`` (comma-separate for several rules; on a
``def``/``class`` line the suppression covers the whole body).
"""

from tools.rtlint.engine import (  # noqa: F401
    AnalysisResult,
    Baseline,
    DEFAULT_TARGETS,
    Finding,
    analyze_paths,
    lint_paths,
    lint_source,
)
from tools.rtlint.project import ProjectModel  # noqa: F401
from tools.rtlint.rules import ALL_RULES, rule_by_id  # noqa: F401

__all__ = [
    "ALL_RULES",
    "AnalysisResult",
    "Baseline",
    "DEFAULT_TARGETS",
    "Finding",
    "ProjectModel",
    "analyze_paths",
    "lint_paths",
    "lint_source",
    "rule_by_id",
]
