"""rtlint --fix: mechanical autofixes for the two fully-local shapes.

Only rewrites whose correctness is decidable from the statement alone
are automated; everything else stays a finding for a human.

- RT004 ``f.remote(...)`` as a bare expression statement: the ref (and
  the task's error) is silently dropped. Rewritten to the leash idiom
  RULES.md prescribes — assign the ref, then reap it with a
  zero-timeout ``rt.wait`` so errors stay observable::

      f.remote(x)
  ->
      _reaped = f.remote(x)
      rt.wait([_reaped], timeout=0)

  Applied only when the module binds the name ``rt`` via an import;
  otherwise the fix is skipped (and reported) rather than introducing
  an undefined name.

- RT013 ``boundaries=[...]`` list literal in a metric registration:
  histograms key aggregation on the boundary object, so the literal is
  frozen in place — ``[`` / ``]`` become ``(`` / ``)``. Single-element
  lists grow a trailing comma so the result stays a tuple.

Both fixes are idempotent: the rewritten form no longer matches the
rule, so a second pass is a no-op (tests assert fix(fix(s)) == fix(s)).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

__all__ = ["fix_source", "FIXABLE_RULES"]

FIXABLE_RULES = ("RT004", "RT013")


def _module_binds_rt(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if bound == "rt":
                    return True
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if (alias.asname or alias.name) == "rt":
                    return True
    return False


def _boundary_lists(tree: ast.Module) -> List[Tuple[ast.Call, ast.List]]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg == "boundaries" and isinstance(kw.value, ast.List):
                out.append((node, kw.value))
    return out


def _bare_remote_stmts(tree: ast.Module) -> List[ast.Expr]:
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "remote"):
            out.append(node)
    return out


def _restrict(nodes: Iterable[ast.AST],
              lines: Optional[Set[int]]) -> List[ast.AST]:
    if lines is None:
        return list(nodes)
    return [n for n in nodes if n.lineno in lines]


def fix_source(source: str, path: str = "<fix>",
               rt004_lines: Optional[Set[int]] = None,
               rt013_lines: Optional[Set[int]] = None,
               ) -> Tuple[str, List[str]]:
    """Rewrite `source`; returns (new_source, human-readable notes).

    `rt004_lines` / `rt013_lines` restrict each fix to findings at
    those 1-based lines (None fixes every match — used by tests);
    passing the analyzer's finding lines keeps suppressed and
    intentionally fire-and-forget sites untouched.
    """
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return source, []
    lines = source.splitlines(keepends=True)
    notes: List[str] = []

    # RT013 first: pure character replacements, line numbers never move.
    # The rule anchors its finding at the registration *call*, so the
    # restriction matches any line from the call head through the list.
    boundary = _boundary_lists(tree)
    if rt013_lines is not None:
        boundary = [
            (call, lst) for call, lst in boundary
            if rt013_lines & set(range(call.lineno, lst.end_lineno + 1))]
    for _call, lst in boundary:
        open_ln, open_col = lst.lineno - 1, lst.col_offset
        close_ln, close_col = lst.end_lineno - 1, lst.end_col_offset - 1
        if (lines[open_ln][open_col] != "["
                or lines[close_ln][close_col] != "]"):
            continue
        comma = ""
        if len(lst.elts) == 1:
            # (x) is not a tuple; (x,) is.
            comma = ","
        lines[close_ln] = (lines[close_ln][:close_col] + comma + ")"
                           + lines[close_ln][close_col + 1:])
        lines[open_ln] = (lines[open_ln][:open_col] + "("
                          + lines[open_ln][open_col + 1:])
        notes.append(f"{path}:{lst.lineno}: RT013 froze boundaries "
                     f"list literal to a tuple")

    # RT004: line insertions — apply bottom-up so earlier linenos stay
    # valid.
    targets = _restrict(_bare_remote_stmts(tree), rt004_lines)
    if targets and not _module_binds_rt(tree):
        notes.append(f"{path}: skipped {len(targets)} discarded-"
                     f"ObjectRef fix(es) — module does not import `rt`, "
                     f"cannot emit the rt.wait leash")
        targets = []
    reap = "_reaped"
    while targets and reap in source:
        reap += "_"
    ref_notes: List[str] = []
    for node in sorted(targets, key=lambda n: n.lineno, reverse=True):
        first = lines[node.lineno - 1]
        indent = first[:node.col_offset]
        if indent.strip():
            # Not alone on its line (`x; f.remote()`): leave for a human.
            ref_notes.append(f"{path}:{node.lineno}: skipped discarded-"
                             f"ObjectRef fix — statement shares its line")
            continue
        lines[node.lineno - 1] = (indent + f"{reap} = "
                                  + first[node.col_offset:])
        last = node.end_lineno - 1
        if not lines[last].endswith("\n"):
            lines[last] += "\n"
        lines.insert(last + 1,
                     f"{indent}rt.wait([{reap}], timeout=0)\n")
        ref_notes.append(f"{path}:{node.lineno}: RT004 leashed "
                         f"discarded ObjectRef (`{reap} = ...; "
                         f"rt.wait(..., timeout=0)`)")
    notes.extend(reversed(ref_notes))
    return "".join(lines), notes
