"""rtlint pass 1: project symbol table and call graph.

``summarize_module`` reduces one parsed file to a plain-dict summary
(JSON-serializable, so it caches and crosses process boundaries for
``--jobs``): its imports, classes, functions with their runtime context
(async, actor method, jit/donate decoration, thread-target), and the
call edges each function makes, recorded as the dotted names written at
the call sites.

``ProjectModel`` joins the summaries: it derives module names from
paths, resolves call-site names through import aliases, ``from``
imports and re-export chains (with a cycle guard), resolves ``self.m``
through the class and its project-local bases, and computes the context
closures pass-2 rules consume:

- ``traced``   — functions whose bodies run under jit tracing (jit
  roots plus functions every project caller of which is traced),
- ``in_async`` — functions running on an event loop (``async def``
  roots plus sync helpers only ever called from async context, minus
  thread targets),
- ``actor_reach`` / ``control_reach`` — functions reachable from
  @rt.remote actor methods / control-plane modules via the call graph,
  each with a witness root for the diagnostic message,
- ``hoppers`` / ``deadline_aware`` — functions that (transitively)
  dispatch downstream work, and those that already handle RequestMeta
  (parameter, thread-local read, or bind), for the RT009 taint rule.

Function identity is ``"<path>::<qualname>"`` — path-keyed so renames
of modules churn fingerprints but edits inside a file do not.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

_JIT_NAMES = {"jit", "pjit"}

# Downstream dispatch: submitting work / bytes to another component.
HOP_ATTRS = {"remote", "submit", "sendall", "redispatch", "_stream_call"}

# Parameter names (or annotation substrings) that carry request
# deadline/meta taint for RT009.
META_PARAMS = {"meta", "request_meta", "deadline_ts"}
META_ANNOTATIONS = ("RequestMeta",)


def module_name_of(path: str) -> str:
    """'ray_tpu/serve/llm.py' -> 'ray_tpu.serve.llm'; __init__ folds."""
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [x for x in p.split("/") if x not in ("", ".")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        inner = _dotted(cur.func)
        if inner:
            parts.append(inner + "()")
    return ".".join(reversed(parts))


def _annotation_str(node: Optional[ast.AST]) -> str:
    if node is None:
        return ""
    try:
        return ast.unparse(node)
    except Exception:
        return ""


class _ModuleSummarizer(ast.NodeVisitor):
    """One pass over a module tree producing the summary dict."""

    def __init__(self, path: str):
        self.path = path
        self.summary: Dict = {
            "path": path,
            "module": module_name_of(path),
            "imports": {},        # local alias -> module
            "from_imports": {},   # local name -> [module, original name]
            "defs": {},           # qualname -> func dict
            "classes": {},        # class name -> class dict
            "jit_passed": [],     # local function names passed to jit()
            "thread_targets": [],  # dotted names given to Thread/executor
            "metric_defs": [],    # metric names registered in this module
            "panel_exprs": [],    # grafana (expr, lineno) pairs
        }
        self._stack: List[Tuple[str, ast.AST]] = []  # (qualname, node)
        self._class_stack: List[str] = []

    # -- imports ----------------------------------------------------------
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            local = a.asname or a.name.split(".")[0]
            self.summary["imports"][local] = (a.name if a.asname
                                              else a.name.split(".")[0])
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                self.summary["from_imports"][a.asname or a.name] = [
                    node.module, a.name]
        elif node.level:  # relative: resolve against this module's package
            pkg = self.summary["module"].split(".")
            # level=1 strips the module's own leaf (or nothing for
            # __init__, whose module name *is* the package).
            is_pkg = self.path.endswith("__init__.py")
            up = node.level - (1 if is_pkg else 0)
            base = pkg[:len(pkg) - up] if up <= len(pkg) else []
            mod = ".".join(base + ([node.module] if node.module else []))
            if mod:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.summary["from_imports"][a.asname or a.name] = [
                        mod, a.name]
        self.generic_visit(node)

    # -- defs -------------------------------------------------------------
    def _qual(self, name: str) -> str:
        return (f"{self._stack[-1][0]}.{name}" if self._stack else name)

    def visit_ClassDef(self, node: ast.ClassDef):
        qual = self._qual(node.name)
        decorators = [_dotted(d.func if isinstance(d, ast.Call) else d)
                      for d in node.decorator_list]
        is_actor = any(d.split(".")[-1] == "remote" for d in decorators)
        if not self._class_stack:  # only index top-level-ish classes
            self.summary["classes"][node.name] = {
                "qualname": qual,
                "bases": [_dotted(b) for b in node.bases],
                "decorators": decorators,
                "is_actor": is_actor,
                "methods": [n.name for n in node.body
                            if isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))],
            }
        self._stack.append((qual, node))
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()
        self._stack.pop()

    def _visit_func(self, node, is_async: bool):
        qual = self._qual(node.name)
        decorators = [_dotted(d.func if isinstance(d, ast.Call) else d)
                      for d in node.decorator_list]
        params = [a.arg for a in (node.args.posonlyargs + node.args.args
                                  + node.args.kwonlyargs)]
        annos = {a.arg: _annotation_str(a.annotation)
                 for a in (node.args.posonlyargs + node.args.args
                           + node.args.kwonlyargs)}
        self.summary["defs"][qual] = {
            "name": node.name,
            "qualname": qual,
            "lineno": node.lineno,
            "is_async": is_async,
            "params": params,
            "decorators": decorators,
            "class": self._class_stack[-1] if self._class_stack else "",
            "is_jit": any(d.split(".")[-1] in _JIT_NAMES
                          for d in decorators),
            "meta_params": sorted(
                {p for p in params if p in META_PARAMS}
                | {p for p, an in annos.items()
                   if any(m in an for m in META_ANNOTATIONS)}),
            "calls": [],
            "hops": False,
            "reads_ctx": False,
            "binds_meta": False,
            "ret_calls": _returned_calls(node),
            "gcs_handler": _handler_info(node),
            "gcs": _gcs_client_info(node),
        }
        self._stack.append((qual, node))
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_func(node, is_async=False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._visit_func(node, is_async=True)

    # -- calls ------------------------------------------------------------
    def _owner(self) -> Optional[Dict]:
        for qual, node in reversed(self._stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self.summary["defs"][qual]
        return None

    def visit_Call(self, node: ast.Call):
        dotted = _dotted(node.func)
        owner = self._owner()
        if owner is not None and dotted:
            owner["calls"].append([dotted, node.lineno])
            leaf = dotted.rsplit(".", 1)[-1]
            if isinstance(node.func, ast.Attribute) and leaf in HOP_ATTRS:
                owner["hops"] = True
            if leaf == "current" and ("context" in dotted
                                      or dotted == "current"):
                owner["reads_ctx"] = True
            if leaf in {"bind", "make_wire_ctx", "set_request_meta"}:
                owner["binds_meta"] = True
        # jit(f) — f becomes a traced root; Thread(target=self.m) /
        # run_in_executor(ex, f) / to_thread(f) — f runs off-loop.
        leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
        if leaf in _JIT_NAMES and node.args:
            fn = node.args[0]
            if isinstance(fn, ast.Name):
                self.summary["jit_passed"].append(
                    self._qual(fn.id) if self._stack else fn.id)
            elif isinstance(fn, ast.Attribute):
                self.summary["jit_passed"].append(_dotted(fn))
        if leaf in {"Counter", "Gauge", "Histogram"} and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            self.summary["metric_defs"].append(node.args[0].value)
        elif leaf == "get_or_create" and len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            self.summary["metric_defs"].append(node.args[1].value)
        if leaf == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    t = _dotted(kw.value)
                    if t:
                        self.summary["thread_targets"].append(t)
        elif leaf in {"run_in_executor", "to_thread", "submit"}:
            idx = 1 if leaf == "run_in_executor" else 0
            if len(node.args) > idx:
                t = _dotted(node.args[idx])
                if t:
                    self.summary["thread_targets"].append(t)
        self.generic_visit(node)


def _returned_calls(node) -> List[str]:
    """Dotted call names whose results this def may return, directly
    (``return pool.alloc(n)``) or through one simple local
    (``x = pool.alloc(n) ... return x``). Nested defs are skipped —
    their returns are their own."""
    assigned: Dict[str, str] = {}
    rets: List[str] = []
    todo: List[ast.stmt] = list(node.body)
    while todo:
        st = todo.pop(0)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and isinstance(st.value, ast.Call):
            d = _dotted(st.value.func)
            if d:
                assigned[st.targets[0].id] = d
        if isinstance(st, ast.Return) and st.value is not None:
            v = st.value
            if isinstance(v, ast.Call):
                d = _dotted(v.func)
                if d and d not in rets:
                    rets.append(d)
            elif isinstance(v, ast.Name) and v.id in assigned:
                if assigned[v.id] not in rets:
                    rets.append(assigned[v.id])
        for child in ast.iter_child_nodes(st):
            if isinstance(child, (ast.stmt, ast.ExceptHandler)):
                todo.append(child)
            elif isinstance(child, (ast.Try, ast.If, ast.For, ast.While,
                                    ast.With)):
                todo.append(child)
            elif hasattr(child, "body") and isinstance(
                    getattr(child, "body", None), list):
                todo.extend(c for c in child.body
                            if isinstance(c, ast.stmt))
    return rets


def _handler_info(node) -> Optional[Dict]:
    """Request/response field surface of one GCS ``h_*`` handler: which
    payload keys it requires (``d["k"]``), reads optionally
    (``d.get("k")``), and which keys its dict-literal responses carry.
    ``req_open``/``resp_open`` mark surfaces we cannot see statically
    (``d`` forwarded whole, non-literal returns)."""
    params = [a.arg for a in node.args.posonlyargs + node.args.args]
    if not node.name.startswith("h_") or "d" not in params[:3]:
        return None
    req, opt, resp = set(), set(), set()
    req_open = resp_open = False
    # Subscripts under a conditional (if/try/loop body) are reads the
    # handler may never reach — optional from the client's view.
    parent: Dict[int, ast.AST] = {}
    for p in ast.walk(node):
        for child in ast.iter_child_nodes(p):
            parent[id(child)] = p

    def _conditional(n) -> bool:
        cur = n
        while id(cur) in parent and cur is not node:
            cur = parent[id(cur)]
            if isinstance(cur, (ast.If, ast.Try, ast.While, ast.For,
                                ast.AsyncFor, ast.IfExp, ast.BoolOp)):
                return True
        return False

    for n in ast.walk(node):
        if isinstance(n, ast.Subscript) \
                and isinstance(n.value, ast.Name) and n.value.id == "d":
            sl = n.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                (opt if _conditional(n) else req).add(sl.value)
            else:
                req_open = True
        elif isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Attribute) and f.attr == "get"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "d" and n.args
                    and isinstance(n.args[0], ast.Constant)
                    and isinstance(n.args[0].value, str)):
                opt.add(n.args[0].value)
            elif any(isinstance(a, ast.Name) and a.id == "d"
                     for a in n.args):
                req_open = True     # d forwarded whole to a helper
        elif isinstance(n, ast.Compare) and isinstance(
                n.left, ast.Constant) and isinstance(n.left.value, str) \
                and len(n.ops) == 1 \
                and isinstance(n.ops[0], (ast.In, ast.NotIn)) \
                and isinstance(n.comparators[0], ast.Name) \
                and n.comparators[0].id == "d":
            # `"k" in d` guard: reads of d["k"] are conditional.
            opt.add(n.left.value)
        elif isinstance(n, ast.Return) and n.value is not None:
            v = n.value
            if isinstance(v, ast.Dict) and v.keys and all(
                    k is not None and isinstance(k, ast.Constant)
                    and isinstance(k.value, str) for k in v.keys):
                resp.update(k.value for k in v.keys)
            else:
                resp_open = True
    return {"required": sorted(req - opt), "optional": sorted(opt),
            "resp": sorted(resp), "req_open": req_open,
            "resp_open": resp_open}


def _unwrap_gcs_method(expr) -> Optional[str]:
    """Method name when `expr` is (an await/_run wrapper around) a
    ``_gcs_call("m", ...)``."""
    if isinstance(expr, ast.Await):
        return _unwrap_gcs_method(expr.value)
    if isinstance(expr, ast.Call):
        f = expr.func
        leaf = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else "")
        if leaf == "_gcs_call" and expr.args \
                and isinstance(expr.args[0], ast.Constant) \
                and isinstance(expr.args[0].value, str):
            return expr.args[0].value
        if leaf == "_run" and expr.args:
            return _unwrap_gcs_method(expr.args[0])
    return None


def _gcs_client_info(node) -> Dict:
    """Call sites + response-key uses of ``_gcs_call`` inside one def."""
    calls: List = []
    resp_uses: List = []
    var_methods: Dict[str, str] = {}
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            leaf = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else "")
            if leaf == "_gcs_call" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                method = n.args[0].value
                keys, literal = None, False
                if len(n.args) < 2:
                    payload = next((kw.value for kw in n.keywords
                                    if kw.arg == "payload"), None)
                else:
                    payload = n.args[1]
                if payload is None:
                    keys, literal = [], True
                elif isinstance(payload, ast.Dict) and all(
                        k is not None and isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        for k in payload.keys):
                    keys = [k.value for k in payload.keys]
                    literal = True
                calls.append({"method": method, "keys": keys,
                              "literal": literal, "lineno": n.lineno})
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            m = _unwrap_gcs_method(n.value)
            if m:
                var_methods[n.targets[0].id] = m
        if isinstance(n, ast.Subscript):
            sl = n.slice
            if not (isinstance(sl, ast.Constant)
                    and isinstance(sl.value, str)):
                continue
            m = _unwrap_gcs_method(n.value)
            if m is None and isinstance(n.value, ast.Name):
                m = var_methods.get(n.value.id)
            if m:
                resp_uses.append([m, sl.value, n.lineno])
    return {"calls": calls, "resp_uses": resp_uses}


def summarize_module(tree: ast.AST, path: str) -> Dict:
    s = _ModuleSummarizer(path)
    s.visit(tree)
    if "dashboard/" in path:
        for n in ast.walk(tree):
            if isinstance(n, ast.Dict):
                for k, v in zip(n.keys, n.values):
                    if (isinstance(k, ast.Constant) and k.value == "expr"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        s.summary["panel_exprs"].append(
                            [v.value, v.lineno])
    # Synthetic metric series emitted as dict documents (the GCS builds
    # its surface this way) count as definitions too.
    for n in ast.walk(tree):
        if isinstance(n, ast.Dict):
            keys = {k.value for k in n.keys
                    if isinstance(k, ast.Constant)}
            if "name" in keys and "type" in keys:
                for k, v in zip(n.keys, n.values):
                    if (isinstance(k, ast.Constant) and k.value == "name"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        s.summary["metric_defs"].append(v.value)
    return s.summary


def empty_summary(path: str) -> Dict:
    """Fallback when a file cannot be parsed/summarized: the project
    model still has an entry, so resolution degrades instead of dying."""
    return {"path": path, "module": module_name_of(path), "imports": {},
            "from_imports": {}, "defs": {}, "classes": {},
            "jit_passed": [], "thread_targets": [], "metric_defs": [],
            "panel_exprs": []}


# -- the project model ----------------------------------------------------
CONTROL_SCOPES = ("serve/", "train/", "util/collective/")


def func_id(path: str, qualname: str) -> str:
    return f"{path}::{qualname}"


class ProjectModel:
    """Symbol table + call graph over a set of module summaries."""

    def __init__(self, summaries: Sequence[Dict]):
        self.modules: Dict[str, Dict] = {}   # module name -> summary
        self.by_path: Dict[str, Dict] = {}   # path -> summary
        for s in summaries:
            self.by_path[s["path"]] = s
            self.modules[s["module"]] = s
        self._resolve_memo: Dict[Tuple[str, str], Optional[str]] = {}
        self.edges: Dict[str, Set[str]] = {}     # caller fid -> callee fids
        self.redges: Dict[str, Set[str]] = {}    # callee fid -> caller fids
        self._build_graph()
        self.thread_target_ids = self._resolve_thread_targets()
        self.traced = self._exclusive_closure(self._traced_roots())
        self.in_async = self._exclusive_closure(
            self._async_roots(), exclude=self.thread_target_ids)
        self.actor_reach = self._witnessed_reach(self._actor_roots())
        self.control_reach = self._witnessed_reach(self._control_roots())
        self.hoppers = self._transitive_flag("hops")
        self.deadline_aware = self._transitive_flag("_aware")

    # -- symbol resolution ------------------------------------------------
    def resolve(self, module: str, name: str,
                _seen: Optional[Set] = None) -> Optional[str]:
        """Resolve a module-level `name` in `module` to a function id,
        following from-import re-export chains. Cycle-safe."""
        key = (module, name)
        if key in self._resolve_memo:
            return self._resolve_memo[key]
        _seen = _seen or set()
        if key in _seen:           # import cycle: give up quietly
            return None
        _seen.add(key)
        out: Optional[str] = None
        ms = self.modules.get(module)
        if ms is not None:
            if name in ms["defs"]:
                out = func_id(ms["path"], name)
            elif name in ms["from_imports"]:
                src_mod, src_name = ms["from_imports"][name]
                out = self.resolve(src_mod, src_name, _seen)
                if out is None and src_mod in self.modules:
                    # `from pkg import mod` pulls in a module object.
                    sub = f"{src_mod}.{src_name}"
                    if sub in self.modules:
                        out = f"<module>::{sub}"
            elif name in ms["imports"]:
                tgt = ms["imports"][name]
                if tgt in self.modules:
                    out = f"<module>::{tgt}"
        self._resolve_memo[key] = out
        return out

    def resolve_class(self, module: str, name: str) -> Optional[Dict]:
        """Resolve a class name visible in `module` to its summary dict
        (annotated with its defining module), following imports."""
        seen = set()
        while True:
            if (module, name) in seen:
                return None
            seen.add((module, name))
            ms = self.modules.get(module)
            if ms is None:
                return None
            if name in ms["classes"]:
                cls = dict(ms["classes"][name])
                cls["_module"] = module
                cls["_path"] = ms["path"]
                return cls
            if name in ms["from_imports"]:
                module, name = ms["from_imports"][name]
                continue
            return None

    def resolve_method(self, module: str, cls_name: str,
                       method: str) -> Optional[str]:
        """Resolve Class.method through the class and its project-local
        bases (method resolution through self)."""
        seen: Set[Tuple[str, str]] = set()
        queue = [(module, cls_name)]
        while queue:
            mod, cname = queue.pop(0)
            if (mod, cname) in seen:
                continue
            seen.add((mod, cname))
            cls = self.resolve_class(mod, cname)
            if cls is None:
                continue
            qual = f"{cls['qualname']}.{method}"
            ms = self.modules.get(cls["_module"])
            if ms and qual in ms["defs"]:
                return func_id(cls["_path"], qual)
            for base in cls["bases"]:
                queue.append((cls["_module"], base.split(".")[-1]))
        return None

    def resolve_call(self, summary: Dict, fn: Dict,
                     dotted: str) -> Optional[str]:
        """Resolve one call-site dotted name written inside `fn`."""
        parts = dotted.split(".")
        module = summary["module"]
        if parts[0] == "self" and len(parts) == 2 and fn["class"]:
            return self.resolve_method(module, fn["class"], parts[1])
        if len(parts) == 1:
            # nested def in the same function first, then module scope
            nested = f"{fn['qualname']}.{parts[0]}"
            if nested in summary["defs"]:
                return func_id(summary["path"], nested)
            return self.resolve(module, parts[0])
        head = self.resolve(module, parts[0])
        if head is None:
            return None
        if head.startswith("<module>::"):
            mod = head.split("::", 1)[1]
            if len(parts) == 2:
                return self.resolve(mod, parts[1])
            if len(parts) == 3:  # mod.Class.method
                return self.resolve_method(mod, parts[1], parts[2])
            return None
        # head is a function/class id: Class.method / Class().method
        path, qual = head.split("::", 1)
        ms = self.by_path.get(path)
        if ms and len(parts) == 2 and qual in ms["classes"]:
            return self.resolve_method(ms["module"], qual, parts[1])
        return None

    # -- graph ------------------------------------------------------------
    def _build_graph(self):
        for s in self.by_path.values():
            for qual, fn in s["defs"].items():
                fid = func_id(s["path"], qual)
                out = self.edges.setdefault(fid, set())
                for dotted, _ in fn["calls"]:
                    callee = self.resolve_call(s, fn, dotted)
                    if callee and "::" in callee and \
                            not callee.startswith("<module>::"):
                        out.add(callee)
        for caller, callees in self.edges.items():
            for c in callees:
                self.redges.setdefault(c, set()).add(caller)

    def func(self, fid: str) -> Optional[Dict]:
        path, qual = fid.split("::", 1)
        ms = self.by_path.get(path)
        return ms["defs"].get(qual) if ms else None

    def _all_funcs(self):
        for s in self.by_path.values():
            for qual, fn in s["defs"].items():
                yield func_id(s["path"], qual), s, fn

    # -- roots ------------------------------------------------------------
    def _traced_roots(self) -> Set[str]:
        roots: Set[str] = set()
        for fid, s, fn in self._all_funcs():
            if fn["is_jit"]:
                roots.add(fid)
        for s in self.by_path.values():
            for name in s["jit_passed"]:
                tgt = None
                if name.startswith("self."):
                    continue  # method handles via is_jit decorators
                if name in s["defs"]:
                    tgt = func_id(s["path"], name)
                else:
                    tgt = self.resolve(s["module"], name.split(".")[-1])
                if tgt and not tgt.startswith("<module>::"):
                    roots.add(tgt)
        return roots

    def _async_roots(self) -> Set[str]:
        return {fid for fid, s, fn in self._all_funcs() if fn["is_async"]}

    def _actor_roots(self) -> Set[str]:
        roots = set()
        for fid, s, fn in self._all_funcs():
            cls = s["classes"].get(fn["class"]) if fn["class"] else None
            if cls and cls["is_actor"]:
                roots.add(fid)
        return roots

    def _control_roots(self) -> Set[str]:
        return {fid for fid, s, fn in self._all_funcs()
                if any(scope in s["path"] for scope in CONTROL_SCOPES)}

    def _resolve_thread_targets(self) -> Set[str]:
        out: Set[str] = set()
        for s in self.by_path.values():
            for dotted in s["thread_targets"]:
                parts = dotted.split(".")
                if parts[0] == "self" and len(parts) == 2:
                    # any class in this module with that method
                    for cname in s["classes"]:
                        m = self.resolve_method(s["module"], cname,
                                                parts[1])
                        if m:
                            out.add(m)
                else:
                    fid = self.resolve(s["module"], parts[-1])
                    if fid and not fid.startswith("<module>::"):
                        out.add(fid)
        return out

    # -- closures ---------------------------------------------------------
    def _exclusive_closure(self, roots: Set[str],
                           exclude: Set[str] = frozenset()) -> Set[str]:
        """Roots plus functions reachable from them — but a reached
        function with any caller *outside* the closure is dropped
        (context is ambiguous; do not over-flag)."""
        closure = set(roots)
        frontier = list(roots)
        while frontier:
            for callee in sorted(self.edges.get(frontier.pop(), ())):
                if callee in closure or callee in exclude:
                    continue
                closure.add(callee)
                frontier.append(callee)
        for fid in sorted(closure - roots):
            callers = self.redges.get(fid, set())
            if any(c not in closure for c in callers):
                closure.discard(fid)
        return closure

    def _witnessed_reach(self, roots: Set[str]) -> Dict[str, str]:
        """fid -> witness root for everything reachable from `roots`."""
        reach: Dict[str, str] = {fid: fid for fid in roots}
        frontier = sorted(roots)
        while frontier:
            cur = frontier.pop(0)
            for callee in sorted(self.edges.get(cur, ())):
                if callee not in reach:
                    reach[callee] = reach[cur]
                    frontier.append(callee)
        return reach

    def _transitive_flag(self, key: str) -> Set[str]:
        """Functions where `key` holds directly or in any callee.
        key="_aware" is the synthetic deadline-aware predicate."""
        direct = set()
        for fid, s, fn in self._all_funcs():
            if key == "_aware":
                if fn["meta_params"] or fn["reads_ctx"] or fn["binds_meta"]:
                    direct.add(fid)
            elif fn.get(key):
                direct.add(fid)
        out = set(direct)
        changed = True
        while changed:
            changed = False
            for caller, callees in self.edges.items():
                if caller not in out and any(c in out for c in callees):
                    out.add(caller)
                    changed = True
        return out

    # -- per-file views consumed by rules ---------------------------------
    def _file_quals(self, path: str, fids) -> Dict[str, str]:
        out = {}
        prefix = f"{path}::"
        for fid in fids:
            if fid.startswith(prefix):
                val = fids[fid] if isinstance(fids, dict) else fid
                out[fid[len(prefix):]] = val
        return out

    def traced_quals(self, path: str) -> Set[str]:
        return set(self._file_quals(path, self.traced))

    def async_quals(self, path: str) -> Set[str]:
        return set(self._file_quals(path, self.in_async))

    def actor_reach_quals(self, path: str) -> Dict[str, str]:
        return self._file_quals(path, self.actor_reach)

    def control_reach_quals(self, path: str) -> Dict[str, str]:
        return self._file_quals(path, self.control_reach)

    def digest_src(self) -> str:
        """Stable serialization of everything pass 2 depends on —
        including the v3 cross-file surfaces (GCS handler fields,
        client payloads, metric defs, panel exprs, resource-returning
        helpers), so editing only a handler invalidates its clients'
        cached findings."""
        import json
        cross = []
        for s in sorted(self.by_path.values(), key=lambda x: x["path"]):
            for qual in sorted(s["defs"]):
                fn = s["defs"][qual]
                h = fn.get("gcs_handler")
                g = fn.get("gcs") or {}
                if h or g.get("calls") or g.get("resp_uses") \
                        or fn.get("ret_calls"):
                    cross.append([s["path"], qual, h, g.get("calls"),
                                  g.get("resp_uses"),
                                  fn.get("ret_calls")])
            if s.get("metric_defs") or s.get("panel_exprs"):
                cross.append([s["path"], s.get("metric_defs"),
                              s.get("panel_exprs")])
        return json.dumps(
            sorted((s["path"], sorted(s["defs"]))
                   for s in self.by_path.values()),
            separators=(",", ":")) + "|" + ",".join(sorted(
                self.traced | self.in_async
                | set(self.actor_reach) | set(self.control_reach)
                | self.hoppers | self.deadline_aware)) + "|" + \
            json.dumps(cross, separators=(",", ":"), sort_keys=True)
