"""jit-related rules: RT001 host-sync, RT002 retrace, RT012 donation.

RT001 and RT002 are the PR 1 bug classes (the 27x-slow eager serving
loop); RT012 encodes the paged-KV donated-buffer hazard from PR 11:
``cow_copy_page``/``decode_paged`` donate their KV operands, so reusing
the donated python name after the call reads a deleted buffer.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import (Rule, _is_jit_expr, _jit_call_sites,
                                     _traced_bodies)

# Host-sync operations: each forces (or implies) a device->host transfer
# the TPU pipeline must drain for.
_SYNC_ATTRS = {"item", "block_until_ready", "copy_to_host"}
_NP_CONVERTERS = {"asarray", "array"}


class HostSyncRule(Rule):
    """RT001: device->host sync reachable from traced or hot-loop code.

    Inside a jit-traced function, ``.item()`` / ``float()`` / ``int()``
    on arrays, ``np.asarray``, ``jax.device_get`` and
    ``block_until_ready`` either fail at trace time or silently force a
    sync on every call. Outside traced code, the same syncs inside a
    ``for``/``while`` body are the per-step host round trips that made
    the serving engine 27x slower than its raw decode floor (PR 1).
    v2: "traced" is call-graph-aware — a helper every project caller of
    which is jit-traced counts as traced too, even across files.
    """

    id = "RT001"
    name = "host-sync-in-hot-path"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        traced = _traced_bodies(ctx)
        traced_nodes: Set[int] = set()
        for t in traced:
            for node in ctx.walk(t):
                traced_nodes.add(id(node))

        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            op = self._sync_op(ctx, node, in_traced=id(node) in traced_nodes)
            if op is None:
                continue
            if id(node) in traced_nodes:
                yield self.finding(
                    ctx, node,
                    f"`{op}` inside a jit-traced function (or a helper "
                    f"only ever called from traced code) forces a "
                    f"device->host sync (or fails at trace time); hoist "
                    f"it out of the traced body",
                    token=op)
            elif ctx.in_loop(node):
                yield self.finding(
                    ctx, node,
                    f"`{op}` inside a loop body syncs host<->device every "
                    f"iteration — batch it, move it off-step, or fetch "
                    f"async (copy_to_host_async) and drain once",
                    token=op)

    @staticmethod
    def _sync_op(ctx: FileContext, call: ast.Call,
                 in_traced: bool) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in _SYNC_ATTRS:
                return f".{func.attr}()"
            if (isinstance(func.value, ast.Name)
                    and func.value.id in ctx.jax_aliases
                    and func.attr in {"device_get", "block_until_ready"}):
                return f"jax.{func.attr}"
            # np.asarray/np.array only matter under tracing (outside,
            # numpy conversions in loops are ordinary host code).
            if (in_traced and isinstance(func.value, ast.Name)
                    and func.value.id in ctx.np_aliases
                    and func.attr in _NP_CONVERTERS):
                return f"np.{func.attr}"
        elif (in_traced and isinstance(func, ast.Name)
                and func.id in {"float", "int", "bool"}
                and len(call.args) == 1
                and not isinstance(call.args[0], ast.Constant)):
            return f"{func.id}()"
        return None


class RetraceRule(Rule):
    """RT002: jit retrace risk.

    ``jax.jit(...)`` evaluated inside a loop body builds a *fresh*
    compiled-function cache every iteration — every call recompiles
    (this, not the math, was most of the serving engine's original 27x
    gap). A ``@jit`` decorator on a def nested in a loop is the same bug.
    A mutable (list/set/dict) ``static_argnums``/``static_argnames``
    spec can be mutated between calls, changing the cache key and
    silently retracing.
    """

    id = "RT002"
    name = "retrace-risk"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for call in _jit_call_sites(ctx):
            if ctx.in_loop(call):
                yield self.finding(
                    ctx, call,
                    "jax.jit called inside a loop body: each iteration "
                    "builds a fresh jit wrapper with an empty cache, so "
                    "every call recompiles — hoist the jit out of the "
                    "loop",
                    token="jit-in-loop")
            for kw in call.keywords:
                if (kw.arg in {"static_argnums", "static_argnames"}
                        and isinstance(kw.value,
                                       (ast.List, ast.Set, ast.Dict))):
                    yield self.finding(
                        ctx, kw.value,
                        f"{kw.arg} given a mutable {type(kw.value).__name__.lower()} "
                        f"literal — mutation between calls changes the "
                        f"cache key and silently retraces; pass a tuple",
                        token=f"static-{kw.arg}")
        for node in ctx.walk():
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and ctx.in_loop(node)
                    and any(_is_jit_expr(ctx, d)
                            for d in node.decorator_list)):
                yield self.finding(
                    ctx, node,
                    f"@jit-decorated def `{node.name}` inside a loop body "
                    f"re-wraps (and re-traces) every iteration — define "
                    f"it once outside the loop",
                    token="jit-def-in-loop")


class DonatedReuseRule(Rule):
    """RT012: donated buffer used again after the jitted call.

    A jit wrapper built with ``donate_argnums`` *deletes* the donated
    operands when called: XLA reuses their memory for the outputs. Using
    the donated python name again before rebinding it reads a dead
    buffer — jax raises on CPU but on TPU with async dispatch this can
    surface as silent corruption (the paged-KV ``cow_copy_page``/
    ``decode_paged`` hazard, PR 11). The safe idiom rebinds at the call:
    ``kv = self._decode(kv, ...)``. Rebinding kills the taint; a use in
    an earlier loop iteration than the call is not tracked (the rule is
    flow-insensitive across loop back-edges — suppress with a comment
    if the loop rebinds before the use).
    """

    id = "RT012"
    name = "donated-buffer-reuse"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        wrappers = self._donating_wrappers(ctx)
        if not wrappers:
            return
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            key = self._wrapper_key(node.func)
            if key not in wrappers:
                continue
            donated = wrappers[key]
            fn = ctx.enclosing_function(node)
            if fn is None:
                continue
            for idx in donated:
                if idx >= len(node.args):
                    continue
                arg = node.args[idx]
                name = self._trackable(arg)
                if name is None:
                    continue
                pretty = name[1] if name[0] == "name" \
                    else f"self.{name[1]}"
                use = self._use_after(ctx, fn, node, name)
                if use is not None:
                    yield self.finding(
                        ctx, use,
                        f"`{pretty}` was donated to `{key[1]}` (donate_"
                        f"argnums) on line {node.lineno} and used again "
                        f"here without rebinding — the buffer was "
                        f"deleted at the call; rebind the result "
                        f"(`{pretty} = {key[1]}(...)`) or drop "
                        f"donation for this operand",
                        token=pretty)
                    continue
                use = self._except_path_use(ctx, fn, node, name)
                if use is not None:
                    yield self.finding(
                        ctx, use,
                        f"`{pretty}` was donated to `{key[1]}` inside "
                        f"a try whose except handler swallows the "
                        f"failure without rebinding it — on the "
                        f"exception path the donated buffer may already "
                        f"be deleted, so this use reads dead memory; "
                        f"rebuild `{pretty}` in the handler or re-raise",
                        token=pretty)

    # -- wrapper discovery ------------------------------------------------
    @staticmethod
    def _donate_indices(call: ast.Call) -> Optional[Tuple[int, ...]]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = []
                    for e in v.elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, int):
                            out.append(e.value)
                    return tuple(out)
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
        return None

    def _donating_wrappers(self, ctx: FileContext) -> Dict:
        """('name', x) / ('attr', x) -> donated index tuple, for every
        `x = jit(..., donate_argnums=...)` / `self.x = jit(...)`."""
        wrappers: Dict = {}
        for node in ctx.walk():
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _is_jit_expr(ctx, node.value)):
                continue
            donated = self._donate_indices(node.value)
            if not donated:
                continue
            for tgt in node.targets:
                key = self._wrapper_key(tgt)
                if key is not None:
                    wrappers[key] = donated
        return wrappers

    @staticmethod
    def _wrapper_key(node: ast.AST):
        if isinstance(node, ast.Name):
            return ("name", node.id)
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return ("attr", node.attr)
        return None

    @staticmethod
    def _trackable(arg: ast.AST):
        if isinstance(arg, ast.Name):
            return ("name", arg.id)
        if (isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "self"):
            return ("attr", arg.attr)
        return None

    @staticmethod
    def _except_path_use(ctx: FileContext, fn: ast.AST, call: ast.Call,
                         name) -> Optional[ast.AST]:
        """Donating call inside a try whose except handler neither
        re-raises nor rebinds the donated name: on the exception path
        the normal-path rebind never ran, so a use in the handler or
        after the try reads a (possibly) dead buffer."""
        cur: ast.AST = call
        parent = ctx.parent(cur)
        enclosing: Optional[ast.Try] = None
        while parent is not None and not isinstance(
                parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
            if isinstance(parent, ast.Try) and any(
                    cur is stmt for stmt in parent.body):
                enclosing = parent
                break
            cur, parent = parent, ctx.parent(parent)
        if enclosing is None or not enclosing.handlers:
            return None

        def stores(scope) -> bool:
            for n in ast.walk(scope):
                if isinstance(n, (ast.Name, ast.Attribute)) \
                        and isinstance(n.ctx, ast.Store) \
                        and DonatedReuseRule._trackable(n) == name:
                    return True
            return False

        swallowing = [h for h in enclosing.handlers
                      if not any(isinstance(n, ast.Raise)
                                 for n in ast.walk(h))
                      and not stores(h)]
        if not swallowing:
            return None
        # a use inside a swallowing handler is the sharpest evidence
        for h in swallowing:
            for n in ast.walk(h):
                if isinstance(n, (ast.Name, ast.Attribute)) \
                        and isinstance(getattr(n, "ctx", None), ast.Load) \
                        and DonatedReuseRule._trackable(n) == name:
                    return n
        # otherwise: first use after the try completes
        try_end = enclosing.end_lineno or enclosing.lineno
        after = [(n.lineno, n) for n in ctx.walk(fn)
                 if isinstance(n, (ast.Name, ast.Attribute))
                 and isinstance(getattr(n, "ctx", None), ast.Load)
                 and DonatedReuseRule._trackable(n) == name
                 and n.lineno > try_end]
        if not after:
            return None
        after.sort(key=lambda t: t[0])
        return after[0][1]

    @staticmethod
    def _use_after(ctx: FileContext, fn: ast.AST, call: ast.Call,
                   name) -> Optional[ast.AST]:
        """First Load of `name` after `call` within `fn` not preceded by
        a rebinding store. Line-ordered (flow-insensitive in loops)."""
        call_end = call.end_lineno or call.lineno
        kills: List[int] = []
        uses: List[Tuple[int, ast.AST]] = []
        for node in ctx.walk(fn):
            if isinstance(node, (ast.Name, ast.Attribute)):
                key = DonatedReuseRule._trackable(node)
                if key != name:
                    continue
                if isinstance(node.ctx, ast.Store):
                    kills.append(node.lineno)
                elif isinstance(node.ctx, ast.Load) and \
                        node.lineno > call_end:
                    # skip the donated arg itself / same-call uses
                    uses.append((node.lineno, node))
        if not uses:
            return None
        uses.sort()
        for line, node in uses:
            if any(call.lineno <= k <= line for k in kills):
                return None   # rebound before (line-wise) this use
            return node
        return None
