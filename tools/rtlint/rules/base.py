"""Shared rule machinery: the Rule base class and jit detection.

Rules receive a ``FileContext`` whose ``.project`` (when run through
``analyze_paths``/``lint_source``) is the pass-1 ``ProjectModel``;
interprocedural rules consult its context closures, falling back to
purely lexical behavior when the model is absent or degraded.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.rtlint.engine import FileContext, Finding

# Names that mean "this code runs under jax.jit tracing".
_JIT_NAMES = {"jit", "pjit"}


class Rule:
    id: str = ""
    name: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str,
                token: str, scope: Optional[str] = None) -> Finding:
        return Finding(
            self.id, ctx.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), message,
            scope=scope if scope is not None else ctx.scope_of(node),
            token=token,
        )


def _dotted(func: ast.AST) -> str:
    """Best-effort dotted name of a call target ('jax.jit', 'rt.get')."""
    parts: List[str] = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def _is_jit_expr(ctx: FileContext, node: ast.AST) -> bool:
    """Does this expression denote jax.jit / jit / pjit (possibly through
    functools.partial)?"""
    if isinstance(node, ast.Name):
        return (node.id in _JIT_NAMES
                and ctx.from_imports.get(node.id, "").startswith("jax"))
    if isinstance(node, ast.Attribute):
        return (node.attr in _JIT_NAMES
                and isinstance(node.value, ast.Name)
                and node.value.id in ctx.jax_aliases)
    if isinstance(node, ast.Call):
        if _is_jit_expr(ctx, node.func):
            return True
        # functools.partial(jax.jit, ...) — the partial IS a jit wrapper.
        if _dotted(node.func) in {"partial", "functools.partial"}:
            return any(_is_jit_expr(ctx, a) for a in node.args)
    return False


def _jit_call_sites(ctx: FileContext) -> Iterator[ast.Call]:
    for node in ctx.walk():
        if isinstance(node, ast.Call) and _is_jit_expr(ctx, node.func):
            yield node


def _traced_bodies(ctx: FileContext) -> List[ast.AST]:
    """Function/lambda nodes whose bodies run under jit tracing: defs
    decorated with jit, callables passed directly to a jit call, and —
    via the project call graph — defs every project caller of which is
    itself traced."""
    traced: List[ast.AST] = []
    local_defs: Dict[Tuple[str, str], ast.AST] = {}
    for node in ctx.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs[(ctx.scope_of(node), node.name)] = node
            if any(_is_jit_expr(ctx, d) for d in node.decorator_list):
                traced.append(node)
    for call in _jit_call_sites(ctx):
        if not call.args:
            continue
        fn = call.args[0]
        if isinstance(fn, ast.Lambda):
            traced.append(fn)
        elif isinstance(fn, ast.Name):
            target = local_defs.get((ctx.scope_of(call), fn.id))
            if target is not None:
                traced.append(target)
    if ctx.project is not None:
        quals = ctx.project.traced_quals(ctx.path)
        for node in ctx.walk():
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and ctx.qualname_of(node) in quals
                    and node not in traced):
                traced.append(node)
    return traced


def no_timeout(call: ast.Call) -> bool:
    """True when the call carries neither timeout= nor **kwargs."""
    names = {kw.arg for kw in call.keywords}
    return "timeout" not in names and None not in names
