"""RT014/RT015/RT016: path-sensitive resource-lifecycle verification.

One shared analysis walks each function's CFG (``tools.rtlint.cfg``)
tracking which local names hold a linear resource (``resources.py``
specs), and reports the exact line sequence on which a resource can
reach a function exit — normal or exceptional — still held, plus
double-releases (the PR 10 ``cancel_bundle`` double-credit shape) and
rebind-while-held loop-carried leaks. Three thin Rule classes split the
findings by resource family:

- **RT014** PagePool pages — the PR 11 leak class: ``alloc`` then an
  exception before the pages are handed to their table.
- **RT015** placement-group bundles and GCS fences/resize obligations —
  the PR 14 release-leak and PR 10 double-credit incidents.
- **RT016** ObjectRefs bound but never awaited/stored (path-sensitive
  superset of RT004's bare-statement case) and explicit lock
  ``acquire()`` without ``release()`` on some path, including locks
  held across ``yield``.

Precision strategy (what keeps the dogfood sweep green): any *use* of a
held name that is not a recognized release — returning it, yielding it,
storing it into an attribute/container, passing it to any call —
transfers ownership and kills tracking. The interprocedural summaries
(``summaries.py``) let ``pages = self._grab(n)`` start tracking and
``self._cleanup(pages)`` count as the release.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.rtlint.cfg import CFG, build_cfg
from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.resources import (ALL_SPECS, LOCK_HINTS, ResourceSpec,
                                    acquire_receiver_ok, receiver_matches)
from tools.rtlint.rules.base import Rule, _dotted
from tools.rtlint.summaries import build_summaries

_MAX_STATES = 20000       # per-function walk budget


def _recv_leaf(func: ast.AST) -> str:
    """Leaf name of a call receiver: `self._pool.alloc` -> '_pool'."""
    if isinstance(func, ast.Attribute):
        v = func.value
        if isinstance(v, ast.Attribute):
            return v.attr
        if isinstance(v, ast.Name):
            return v.id
        if isinstance(v, ast.Call):
            return _recv_leaf(v.func)
    return ""


def _unwrap_await(expr: ast.AST) -> ast.AST:
    return expr.value if isinstance(expr, ast.Await) else expr


def _arg_names(call: ast.Call) -> Set[str]:
    """Simple Name arguments, looking through list/tuple/starred
    wrappers (`pool.release([p])`, `rt.get(*refs)`)."""
    out: Set[str] = set()
    todo: List[ast.AST] = list(call.args) + [kw.value
                                             for kw in call.keywords]
    while todo:
        a = todo.pop()
        if isinstance(a, ast.Name):
            out.add(a.id)
        elif isinstance(a, (ast.List, ast.Tuple, ast.Set)):
            todo.extend(a.elts)
        elif isinstance(a, ast.Starred):
            todo.append(a.value)
    return out


def _shallow_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """Expression roots evaluated by this statement *itself* (compound
    statements contribute only their heads — the CFG hands us their
    bodies as separate nodes)."""
    if isinstance(stmt, ast.If) or isinstance(stmt, ast.While):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return list(stmt.body)      # closure capture = escape
    if isinstance(stmt, ast.ClassDef):
        return list(stmt.body)
    if isinstance(stmt, ast.Try):
        return []
    if isinstance(stmt, ast.ExceptHandler):
        # The handler *head* evaluates only its type expression; the
        # body arrives as separate CFG nodes.
        return [stmt.type] if stmt.type is not None else []
    return [stmt]


class _Events:
    """Per-CFG-node lifecycle events, precomputed once."""

    __slots__ = ("acquires", "releases", "release_any", "release_kinds",
                 "used", "assigned", "is_yield", "line")

    def __init__(self):
        self.acquires: List[Tuple[str, ResourceSpec]] = []
        # (var, spec) releases by name; "<any>" releases the kind's
        # synthetic (non-name-bound) obligations.
        self.releases: List[Tuple[str, ResourceSpec]] = []
        self.release_any: Set[str] = set()     # kinds released w/o a name
        self.release_kinds: Set[str] = set()   # coarse helper-kill kinds
        self.used: Set[str] = set()            # names read (escape check)
        self.assigned: Set[str] = set()        # simple Name targets
        self.is_yield = False
        self.line = 0


def _extract_events(cfg: CFG, idx: int, ctx: FileContext,
                    summary: Optional[Dict], fn_sum: Optional[Dict],
                    summaries) -> _Events:
    ev = _Events()
    stmt = cfg.stmts[idx]
    if stmt is None:
        return ev
    ev.line = getattr(stmt, "lineno", 0)
    roots = _shallow_exprs(stmt)
    # Nested defs/classes contribute only *reads* (closure capture is
    # an escape); their internal calls run later, not at the def site.
    opaque = isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                ev.assigned.add(t.id)
    elif isinstance(stmt, ast.AugAssign) and isinstance(
            stmt.target, ast.Name):
        ev.assigned.add(stmt.target.id)
    for root in roots:
        for n in ast.walk(root):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                ev.used.add(n.id)
            if opaque:
                continue
            if isinstance(n, (ast.Yield, ast.YieldFrom)):
                ev.is_yield = True
            if isinstance(n, ast.Await) and isinstance(
                    n.value, ast.Name):
                # `await ref` consumes the ref.
                ev.releases.append((n.value.id, _REF_SPEC))
    if opaque:
        return ev

    # Calls: releases / consumes / arg-form acquires / helper summaries.
    calls: List[ast.Call] = []
    for root in roots:
        for n in ast.walk(root):
            if isinstance(n, ast.Call):
                calls.append(n)
    for call in calls:
        func = call.func
        leaf = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else "")
        if not leaf:
            continue
        recv = _recv_leaf(func)
        names = _arg_names(call)
        for spec in ALL_SPECS:
            if leaf in spec.release and receiver_matches(
                    recv, spec.release_hints):
                if names:
                    for nm in names:
                        ev.releases.append((nm, spec))
                else:
                    ev.release_any.add(spec.kind)
            if leaf in spec.consume and isinstance(func, ast.Attribute) \
                    and isinstance(func.value, ast.Name):
                ev.releases.append((func.value.id, spec))
            if leaf in spec.acquire_arg and receiver_matches(
                    recv, spec.acquire_hints):
                # Arg-form acquires (incref, arm_fence) create an
                # *obligation on a token*, not ownership of the name —
                # tracked as a synthetic var so later uses of the token
                # don't count as ownership transfer.
                if names:
                    for nm in sorted(names):
                        ev.acquires.append(
                            (f"<{spec.kind}:{nm}@{ev.line}>", spec))
                else:
                    ev.acquires.append((f"<{spec.kind}@{ev.line}>", spec))
        # Explicit lock acquire: `lock.acquire()` tracks the receiver.
        if leaf == "acquire" and isinstance(func, ast.Attribute):
            recv_dotted = _dotted(func.value)
            if recv_dotted and receiver_matches(
                    recv_dotted.split(".")[-1], LOCK_HINTS):
                ev.acquires.append((recv_dotted, _LOCK_SPEC))
        if leaf == "release" and isinstance(func, ast.Attribute):
            recv_dotted = _dotted(func.value)
            if recv_dotted:
                ev.releases.append((recv_dotted, _LOCK_SPEC))
        # Interprocedural: a project helper known to release kind K.
        if summaries is not None and summary is not None \
                and fn_sum is not None:
            dotted = _dotted(func)
            if dotted:
                kinds = summaries.call_releases(summary, fn_sum, dotted)
                if kinds:
                    if names:
                        for spec in ALL_SPECS:
                            if spec.kind in kinds:
                                for nm in names:
                                    ev.releases.append((nm, spec))
                    else:
                        ev.release_kinds |= kinds

    # Value-binding acquires: `x = [await] recv.leaf(...)`.
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name):
        value = _unwrap_await(stmt.value)
        if isinstance(value, ast.Call):
            func = value.func
            leaf = (func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else "")
            recv = _recv_leaf(func)
            var = stmt.targets[0].id
            for spec in ALL_SPECS:
                if leaf in spec.acquire_value and acquire_receiver_ok(
                        spec, recv):
                    ev.acquires.append((var, spec))
            if summaries is not None and summary is not None \
                    and fn_sum is not None:
                dotted = _dotted(func)
                if dotted:
                    for kind in summaries.call_returns_fresh(
                            summary, fn_sum, dotted):
                        for spec in ALL_SPECS:
                            if spec.kind == kind and not any(
                                    v == var for v, _ in ev.acquires):
                                ev.acquires.append((var, spec))
    return ev


_REF_SPEC = next(s for s in ALL_SPECS if s.kind == "ref")
_LOCK_SPEC = next(s for s in ALL_SPECS if s.kind == "lock")
_SPEC_BY_KIND = {s.kind: s for s in ALL_SPECS}


class _Held:
    __slots__ = ("kind", "line", "released")

    def __init__(self, kind: str, line: int, released: int = 0):
        self.kind = kind
        self.line = line
        self.released = released   # line of the release, 0 = held

    def sig(self):
        return (self.kind, self.line, self.released)


class _RawFinding:
    __slots__ = ("rule", "var", "kind", "acq_line", "line", "shape",
                 "path")

    def __init__(self, rule, var, kind, acq_line, line, shape, path):
        self.rule = rule
        self.var = var
        self.kind = kind
        self.acq_line = acq_line
        self.line = line
        self.shape = shape     # leak / leak-raise / double / rebind / yield
        self.path = path


def _walk(cfg: CFG, events: Dict[int, _Events]) -> List[_RawFinding]:
    """DFS over (node, state) pairs; state maps var -> _Held."""
    out: List[_RawFinding] = []
    reported: Set[Tuple] = set()

    def report(rule, var, h: "_Held", line, shape, path):
        key = (rule, var, h.kind, h.line, shape)
        if key in reported:
            return
        reported.add(key)
        out.append(_RawFinding(rule, var, h.kind, h.line, line, shape,
                               path))

    seen: Set[Tuple] = set()
    # (node, state, path-lines)
    stack: List[Tuple[int, Dict[str, _Held], List[int]]] = [
        (CFG.ENTRY, {}, [])]
    steps = 0
    while stack and steps < _MAX_STATES:
        steps += 1
        node, state, path = stack.pop()
        sig = (node, tuple(sorted((v, h.sig()) for v, h in
                                  state.items())))
        if sig in seen:
            continue
        seen.add(sig)

        if node == cfg.exit:
            for var, h in state.items():
                if not h.released:
                    spec = _SPEC_BY_KIND[h.kind]
                    report(spec.rule, var, h, h.line, "leak", path)
            continue
        if node == cfg.raise_exit:
            for var, h in state.items():
                spec = _SPEC_BY_KIND[h.kind]
                if not h.released and spec.leak_on_raise:
                    report(spec.rule, var, h, h.line, "leak-raise", path)
            continue

        ev = events[node]
        line = ev.line
        npath = path + [line] if line else path
        if len(npath) > 80:
            npath = npath[-80:]

        # -- exceptional post-state: releases/escapes apply, acquires
        # and rebinds do not (the raise may precede the bind).
        exc_state: Optional[Dict[str, _Held]] = None
        has_exc = any(lbl in ("exc", "raise")
                      for _t, lbl in cfg.succ.get(node, ()))

        def apply_uses(st: Dict[str, _Held]) -> Dict[str, _Held]:
            st = dict(st)
            released_here: Set[str] = set()
            for var, spec in ev.releases:
                # A named release lifts both the named binding and any
                # synthetic obligation armed on that token.
                targets = [var] + [v for v in st
                                   if v.startswith(f"<{spec.kind}:{var}@")]
                for v in targets:
                    h = st.get(v)
                    if h is None or h.kind != spec.kind:
                        continue
                    if h.released and spec.double_release:
                        report(spec.rule, v, h, line, "double", npath)
                    st[v] = _Held(h.kind, h.line, released=line)
                    released_here.add(v)
            for kind in ev.release_any | ev.release_kinds:
                for var, h in list(st.items()):
                    if h.kind == kind and var.startswith("<"):
                        st[var] = _Held(h.kind, h.line, released=line)
                        released_here.add(var)
                if kind in ev.release_kinds:
                    # coarse helper kill: stop tracking the kind
                    for var, h in list(st.items()):
                        if h.kind == kind:
                            del st[var]
            # Escapes: any other read of a held name transfers
            # ownership — stop tracking. Synthetic obligations
            # transfer when their *token* is handed to another call,
            # unless the spec says the token is a plain id.
            for var in list(st.keys()):
                if st[var].released or var in released_here:
                    continue
                if var.startswith("<"):
                    spec = _SPEC_BY_KIND[st[var].kind]
                    tok = var.strip("<>").split("@")[0]
                    tok = tok.split(":", 1)[1] if ":" in tok else ""
                    if spec.escape_transfers and tok \
                            and tok in ev.used:
                        del st[var]
                elif var in ev.used:
                    del st[var]
            return st

        nstate = apply_uses(state)
        if has_exc:
            exc_state = nstate

        # Locks across yield: report before the acquire step.
        if ev.is_yield:
            for var, h in nstate.items():
                if h.kind == "lock" and not h.released:
                    report("RT016", var, h, line, "yield", npath)

        # Rebinds and acquires (normal successors only).
        for var in ev.assigned:
            h = nstate.get(var)
            if h is not None and not h.released \
                    and not any(v == var for v, _ in ev.acquires):
                spec = _SPEC_BY_KIND[h.kind]
                report(spec.rule, var, h, line, "rebind", npath)
                nstate = dict(nstate)
                del nstate[var]
        for var, spec in ev.acquires:
            h = nstate.get(var)
            if h is not None and not h.released:
                if var in ev.assigned:
                    # rebind-with-fresh-acquire over a held resource
                    report(spec.rule, var, h, line, "rebind", npath)
            nstate = dict(nstate)
            nstate[var] = _Held(spec.kind, line)

        for dst, lbl in cfg.succ.get(node, ()):
            st = exc_state if (lbl in ("exc", "raise")
                               and exc_state is not None) else nstate
            stack.append((dst, st, npath))
    return out


def _analyze(ctx: FileContext) -> List[_RawFinding]:
    cached = getattr(ctx, "_lifecycle_findings", None)
    if cached is not None:
        return cached
    model = ctx.project
    summaries = None
    summary = None
    if model is not None:
        summaries = getattr(model, "_lifecycle_summaries", None)
        if summaries is None:
            summaries = build_summaries(model)
            model._lifecycle_summaries = summaries
        summary = model.by_path.get(ctx.path)

    out: List[_RawFinding] = []
    for node in ctx.walk():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fn_sum = None
        if summary is not None:
            fn_sum = summary["defs"].get(ctx.qualname_of(node))
        try:
            cfg = build_cfg(node)
        except RecursionError:       # pathological nesting: skip
            continue
        events = {i: _extract_events(cfg, i, ctx, summary, fn_sum,
                                     summaries)
                  for i in range(len(cfg.stmts))}
        if not any(e.acquires for e in events.values()):
            continue
        raws = _walk(cfg, events)
        # A lock held across yield already reports the yield finding;
        # the GeneratorExit raise-path leak it implies is the same bug.
        yielded = {(r.var, r.acq_line) for r in raws
                   if r.shape == "yield"}
        for raw in raws:
            if raw.kind == "lock" and raw.shape == "leak-raise" \
                    and (raw.var, raw.acq_line) in yielded:
                continue
            raw.path = raw.path or [raw.acq_line]
            out.append(raw)
    ctx._lifecycle_findings = out
    return out


def _fmt_path(path: List[int], acq_line: int) -> str:
    lines: List[int] = []
    for ln in path:
        if ln and (not lines or lines[-1] != ln) and ln >= acq_line:
            lines.append(ln)
    if len(lines) > 8:
        lines = lines[:3] + [0] + lines[-4:]
    return " -> ".join("..." if ln == 0 else str(ln) for ln in lines) \
        or str(acq_line)


class _LifecycleRule(Rule):
    """Shared reporting for the three lifecycle families."""

    def _node_for_line(self, ctx: FileContext, line: int) -> ast.AST:
        best = ctx.tree
        for n in ctx.walk():
            if getattr(n, "lineno", None) == line and isinstance(
                    n, ast.stmt):
                return n
        return best

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for raw in _analyze(ctx):
            if raw.rule != self.id:
                continue
            spec = _SPEC_BY_KIND[raw.kind]
            node = self._node_for_line(ctx, raw.acq_line)
            pretty = raw.var
            if pretty.startswith("<"):    # synthetic obligation token
                inner = pretty.strip("<>").split("@")[0]
                pretty = inner.split(":", 1)[1] if ":" in inner else inner
            p = _fmt_path(raw.path, raw.acq_line)
            if raw.shape == "leak":
                msg = (f"{spec.noun} `{pretty}` acquired at line "
                       f"{raw.acq_line} reaches function exit still "
                       f"held (path {p}); {spec.advice}")
            elif raw.shape == "leak-raise":
                msg = (f"{spec.noun} `{pretty}` acquired at line "
                       f"{raw.acq_line} leaks on an exception path "
                       f"(path {p}); {spec.advice}")
            elif raw.shape == "double":
                msg = (f"{spec.noun} `{pretty}` released twice on one "
                       f"path (second release at line {raw.line}, path "
                       f"{p}) — the double-credit shape corrupts "
                       f"accounting; release exactly once per exit path")
            elif raw.shape == "rebind":
                msg = (f"`{pretty}` rebound at line {raw.line} while "
                       f"still holding {spec.noun} from line "
                       f"{raw.acq_line} (loop-carried leak); release "
                       f"before reacquiring")
            else:  # yield
                msg = (f"lock `{pretty}` acquired at line "
                       f"{raw.acq_line} is held across a yield at line "
                       f"{raw.line} — the consumer controls when (or "
                       f"whether) the generator resumes; release "
                       f"first or use `with` inside the loop")
            yield self.finding(ctx, node, msg, token=pretty)


class PageLifecycleRule(_LifecycleRule):
    """RT014: PagePool pages leak/double-free on some path.

    The PR 11 incident class: ``alloc`` (or ``ref``/``incref``)
    succeeds, a later step on the same path raises or returns early,
    and the pages are never released — the pool's free list shrinks
    forever under churn. All-or-nothing rollback on the error path is
    the contract.
    """

    id = "RT014"
    name = "pagepool-lifecycle"


class BundleLifecycleRule(_LifecycleRule):
    """RT015: placement-group bundles / fences leak or double-release.

    Encodes two shipped bugs: the PR 14 release leak (reserved bundles
    never released on an error path, wedging the placement group) and
    the PR 10 ``cancel_bundle`` double-credit (bundle credited twice,
    corrupting node accounting). Fences/resize obligations follow the
    same shape: armed on entry, must be lifted on *every* claimant
    exit path.
    """

    id = "RT015"
    name = "bundle-fence-lifecycle"


class RefLockLifecycleRule(_LifecycleRule):
    """RT016: ObjectRefs bound-then-dropped; locks leaked across paths.

    Path-sensitive superset of RT004: a ref assigned to a local that no
    path ever awaits, gets, cancels, stores, or returns silently drops
    the task's error and pins its result in the object store. Also
    flags explicit lock ``acquire()`` with a release-free path and
    locks held across ``yield`` (the consumer controls resumption).
    """

    id = "RT016"
    name = "ref-lock-lifecycle"
