"""RT009: deadline/RequestMeta taint must flow into downstream hops.

PR 8 built absolute-deadline propagation handle→proxy→replica→engine;
its hardest bugs were *drops*: a function that received the deadline
and then dispatched downstream work without it, silently converting a
bounded request into an unbounded one. This rule is the interprocedural
encoding: receiving ``deadline_ts``/``meta``/``RequestMeta`` makes a
function responsible for every hop it performs or delegates.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule, _dotted
from tools.rtlint.project import HOP_ATTRS, META_ANNOTATIONS, META_PARAMS


class DeadlineTaintRule(Rule):
    """RT009: received deadline/RequestMeta not forwarded downstream.

    A function that *holds* the request deadline — a parameter named
    ``deadline_ts``/``meta``/``request_meta``, a parameter annotated
    ``RequestMeta``, or a local it constructs under one of those names
    — and then performs a downstream hop
    (``.remote(...)``, engine ``submit``, socket ``sendall``,
    ``redispatch``/``_stream_call``) without the tainted value anywhere
    in the hop's arguments has dropped the deadline: the downstream work
    runs unbounded and cancel chains break mid-request (the PR 8 bug
    class). Binding the thread-local card (``with bind(meta):`` /
    ``make_wire_ctx``) counts as forwarding — the hop reads it
    implicitly. Interprocedurally, calling a project function that
    (transitively) hops *and advertises a meta parameter* without
    passing the taint is the same drop, flagged at the delegating call
    — that call site is the one place the deadline could have flowed.
    """

    id = "RT009"
    name = "deadline-drop"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.walk():
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            taints = self._tainted_params(node) \
                | self._tainted_locals(node)
            if not taints:
                continue
            yield from self._check_function(ctx, node, taints)

    @staticmethod
    def _tainted_params(fn) -> Set[str]:
        out: Set[str] = set()
        for a in (fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs):
            if a.arg in META_PARAMS:
                out.add(a.arg)
            elif a.annotation is not None:
                try:
                    anno = ast.unparse(a.annotation)
                except Exception:
                    anno = ""
                if any(m in anno for m in META_ANNOTATIONS):
                    out.add(a.arg)
        return out

    @staticmethod
    def _tainted_locals(fn) -> Set[str]:
        """Constructing the deadline locally (``deadline_ts = ...``,
        ``meta = RequestMeta(...)``) makes the function just as
        responsible for forwarding it as receiving it would. Own body
        only: a nested def that builds its own deadline owns it (and is
        analyzed on its own visit)."""
        out: Set[str] = set()
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in META_PARAMS:
                    out.add(t.id)
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _check_function(self, ctx: FileContext, fn,
                        taints: Set[str]) -> Iterator[Finding]:
        if self._binds(ctx, fn, taints):
            return  # thread-local card bound: hops read it implicitly
        qual = ctx.qualname_of(fn)
        for node in ctx.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_function(node) is not fn and \
                    not self._same_body(ctx, fn, node):
                continue
            func = node.func
            # direct hop without the taint in its arguments
            if isinstance(func, ast.Attribute) and \
                    func.attr in HOP_ATTRS:
                if not self._mentions_taint(node, taints):
                    pretty = _dotted(func) or f".{func.attr}"
                    yield self.finding(
                        ctx, node,
                        f"`{qual}` received the request deadline "
                        f"({'/'.join(sorted(taints))}) but dispatches "
                        f"`{pretty}(...)` without it — downstream work "
                        f"runs unbounded and the cancel chain breaks; "
                        f"forward the meta (or bind the thread-local "
                        f"card first)",
                        token=f".{func.attr}")
                continue
            # delegated hop: project callee that hops but cannot see
            # the deadline, called without the taint
            yield from self._check_delegation(ctx, fn, qual, node, taints)

    def _check_delegation(self, ctx: FileContext, fn, qual: str,
                          node: ast.Call,
                          taints: Set[str]) -> Iterator[Finding]:
        project = ctx.project
        if project is None:
            return
        summary = project.by_path.get(ctx.path)
        if summary is None:
            return
        fsum = summary["defs"].get(qual)
        if fsum is None:
            return
        dotted = _dotted(node.func)
        if not dotted or dotted.rsplit(".", 1)[-1] in HOP_ATTRS:
            return
        callee = project.resolve_call(summary, fsum, dotted)
        if not callee or callee.startswith("<module>::"):
            return
        if callee not in project.hoppers:
            return
        # Only deadline-aware callees are a drop when called bare: they
        # advertise a meta parameter (or read the bound card), so this
        # call site is the one place the taint could have flowed.
        # Hoppers that take no meta are routinely control-plane helpers
        # (routing refresh, membership probes) whose traffic does not
        # carry the request deadline by design.
        if callee not in project.deadline_aware:
            return
        if self._mentions_taint(node, taints):
            return
        cname = callee.split("::", 1)[-1]
        yield self.finding(
            ctx, node,
            f"`{qual}` received the request deadline "
            f"({'/'.join(sorted(taints))}) but calls `{cname}` — "
            f"which dispatches downstream work and accepts the "
            f"meta — without passing it; the deadline is dropped "
            f"at this hop boundary",
            token=dotted.rsplit(".", 1)[-1])

    # -- helpers ----------------------------------------------------------
    @classmethod
    def _same_body(cls, ctx: FileContext, fn, node) -> bool:
        """node's enclosing function is fn itself, a lambda inside fn,
        or a nested closure that receives no meta of its own — such a
        closure sees fn's locals, so its hops are fn's hops. Nested
        defs with their own tainted parameters own their analysis."""
        cur = ctx.enclosing_function(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and cls._tainted_params(cur):
                return False
            cur = ctx.enclosing_function(cur)
        return cur is fn

    @staticmethod
    def _binds(ctx: FileContext, fn, taints: Set[str]) -> bool:
        for node in ctx.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            leaf = _dotted(node.func).rsplit(".", 1)[-1]
            if leaf in {"bind", "make_wire_ctx", "set_request_meta"}:
                if any(isinstance(a, ast.Name) and a.id in taints
                       for a in node.args) or not node.args:
                    return True
                # bind(meta.something) / bind(RequestMeta(...))
                for a in node.args:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Name) and sub.id in taints:
                            return True
        return False

    @staticmethod
    def _mentions_taint(call: ast.Call, taints: Set[str]) -> bool:
        for part in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(part):
                if isinstance(sub, ast.Name) and sub.id in taints:
                    return True
        return False
