"""RT013: metrics discipline — stable boundaries, bounded label sets.

``ray_tpu.util.metrics`` aggregates histograms by *identity* of their
boundary tuples and exports one time series per distinct tag set.
Mutating a shared boundary sequence corrupts every histogram already
bucketed against it; tagging a metric with a per-request value (rid,
idem_key, prompt text, raw hash) makes series cardinality grow with
traffic until the registry is effectively an unbounded log.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule, _dotted


_METRIC_FNS = {"inc", "set", "observe", "inc_keyed", "set_keyed",
               "observe_keyed", "labels"}
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "sort", "reverse", "__setitem__"}
# identifiers that are per-request by repo convention; "tenant" rides
# in on every trace header, so it is unbounded unless validated against
# a fixed admission table (the suppression case).
_REQUEST_IDS = {"rid", "request_id", "req_id", "idem_key", "trace_id",
                "prompt", "span_id", "tenant", "tenant_id"}
_HASHERS = {"hash", "hexdigest", "md5", "sha1", "sha256", "uuid4",
            "uuid1", "token_hex"}


class MetricsDisciplineRule(Rule):
    """RT013: mutated histogram boundaries / unbounded metric labels.

    Two shapes. (a) Boundary mutation: any in-place mutation of a
    ``*BOUNDARIES*``-named sequence (``.append``/``.sort``/subscript
    store/augassign) or passing a mutable ``boundaries=[...]`` list
    literal — boundaries are aggregation keys and must be immutable
    tuples frozen at import. (b) Cardinality: a metric call (``inc``/
    ``set``/``observe``/``*_keyed``/``labels``) whose tag *value* is a
    per-request identifier (``rid``/``request_id``/``idem_key``/
    ``trace_id``/``prompt``…), an f-string or ``str()`` of one, or a
    fresh hash/uuid — each request mints a new time series and the
    registry grows without bound. Tag with the bounded dimension
    (tenant *from admission config*, model, replica role) instead; a
    deliberately-bounded value that merely looks per-request (e.g. a
    tenant id validated against a fixed admission table) is the
    suppression case — say where the bound comes from.
    """

    id = "RT013"
    name = "metrics-discipline"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.walk():
            if isinstance(node, ast.Call):
                yield from self._check_boundary_mutation_call(ctx, node)
                yield from self._check_boundary_literal(ctx, node)
                yield from self._check_cardinality(ctx, node)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                yield from self._check_boundary_store(ctx, node)

    # -- (a) boundary mutation -------------------------------------------
    @staticmethod
    def _is_boundary_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name) and "BOUNDARIES" in node.id.upper():
            return node.id
        if isinstance(node, ast.Attribute) and \
                "BOUNDARIES" in node.attr.upper():
            return node.attr
        return None

    def _check_boundary_mutation_call(self, ctx: FileContext,
                                      node: ast.Call) -> Iterator[Finding]:
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _MUTATORS):
            return
        name = self._is_boundary_name(func.value)
        if name is None:
            return
        yield self.finding(
            ctx, node,
            f"`{name}.{func.attr}(...)` mutates histogram boundaries "
            f"in place — boundaries are aggregation keys shared by "
            f"every histogram bucketed against them; build a new tuple "
            f"instead",
            token=name)

    def _check_boundary_store(self, ctx: FileContext,
                              node) -> Iterator[Finding]:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            if isinstance(tgt, ast.Subscript):
                name = self._is_boundary_name(tgt.value)
                if name is not None:
                    yield self.finding(
                        ctx, node,
                        f"subscript store into `{name}` rewrites a "
                        f"bucket edge under live histograms — "
                        f"boundaries must stay frozen; build a new "
                        f"tuple and re-register",
                        token=name)
            elif isinstance(node, ast.AugAssign):
                name = self._is_boundary_name(tgt)
                if name is not None:
                    yield self.finding(
                        ctx, node,
                        f"augmented assignment to `{name}` mutates "
                        f"shared histogram boundaries — build a new "
                        f"tuple instead",
                        token=name)

    def _check_boundary_literal(self, ctx: FileContext,
                                node: ast.Call) -> Iterator[Finding]:
        for kw in node.keywords:
            if kw.arg == "boundaries" and isinstance(kw.value, ast.List):
                yield self.finding(
                    ctx, node,
                    "boundaries= passed as a mutable list literal — "
                    "histograms key aggregation on the boundary object; "
                    "pass a tuple so it cannot be mutated after "
                    "registration",
                    token="boundaries")

    # -- (b) label cardinality -------------------------------------------
    def _check_cardinality(self, ctx: FileContext,
                           node: ast.Call) -> Iterator[Finding]:
        func = node.func
        leaf = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None)
        if leaf not in _METRIC_FNS:
            return
        # collect candidate tag values: tags={...} dict values,
        # labels(**) keyword values, key= for *_keyed
        values = []
        for kw in node.keywords:
            if kw.arg in ("tags", "labels") and \
                    isinstance(kw.value, ast.Dict):
                values.extend((v, self._dict_key(k))
                              for k, v in zip(kw.value.keys,
                                              kw.value.values))
            elif kw.arg == "key":
                values.append((kw.value, "key"))
            elif leaf == "labels" and kw.arg is not None:
                values.append((kw.value, kw.arg))
        for value, label in values:
            why = self._per_request(value)
            if why is None:
                continue
            yield self.finding(
                ctx, node,
                f"metric tag `{label}` is fed a per-request value "
                f"({why}) — every request mints a new time series and "
                f"the registry grows without bound; tag with a bounded "
                f"dimension (tenant from admission config, model, "
                f"replica role) and put request ids in logs/traces",
                token=str(label))

    @staticmethod
    def _dict_key(k) -> str:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            return k.value
        return "<tag>"

    @classmethod
    def _per_request(cls, value: ast.AST) -> Optional[str]:
        """Returns a human reason if the expression is per-request."""
        def leaf_id(n) -> Optional[str]:
            if isinstance(n, ast.Name):
                return n.id
            if isinstance(n, ast.Attribute):
                return n.attr
            return None

        name = leaf_id(value)
        if name is not None and name.lower() in _REQUEST_IDS:
            return f"`{name}`"
        if isinstance(value, ast.JoinedStr):
            for part in ast.walk(value):
                if isinstance(part, ast.FormattedValue):
                    inner = leaf_id(part.value)
                    if inner and inner.lower() in _REQUEST_IDS:
                        return f"f-string of `{inner}`"
        if isinstance(value, ast.Call):
            dotted = _dotted(value.func)
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf in _HASHERS:
                return f"fresh `{leaf}(...)` value"
            if leaf == "str" and value.args:
                inner = leaf_id(value.args[0])
                if inner and inner.lower() in _REQUEST_IDS:
                    return f"str() of `{inner}`"
        return None
