"""Thread-safety rules: RT006 cross-thread races, RT010 lock discipline.

RT006 (PR 3) catches classes that share bare attributes with their own
background thread. RT010 generalizes the ``dcn_group._accepted`` and
PR 12 alive-flag incidents: once a class protects an attribute with
``with self._lock`` on *any* write, every other method touching it bare
is claiming a happens-before relationship the lock was bought to
provide — usually wrongly.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule, _dotted


def _self_accesses(ctx: FileContext, method: ast.AST):
    """Yields (attr, 'read'|'write', node, locked) for self.X uses.
    A subscript/augmented store through self.X counts as a write of
    X's contents."""
    for node in ctx.walk(method):
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            continue
        kind = "read"
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            kind = "write"
        else:
            parent = ctx.parent(node)
            if (isinstance(parent, ast.Subscript)
                    and isinstance(parent.ctx, (ast.Store, ast.Del))):
                kind = "write"
            elif isinstance(parent, ast.AugAssign) and \
                    parent.target is node:
                kind = "write"
        yield node.attr, kind, node, ctx.under_lock(node)


_SYNC_HINTS = ("lock", "event", "cond", "sem", "mutex")


class ThreadRaceRule(Rule):
    """RT006: unlocked cross-thread attribute access.

    For every class that starts a ``threading.Thread`` on one of its own
    methods, partition methods into thread-side (the target and
    everything it transitively calls on self) and caller-side. An
    attribute *written* without a lock on one side and *accessed*
    without a lock on the other is a data race candidate. ``__init__``
    writes are exempt (they happen-before the thread start); attributes
    whose names say lock/event/cond are synchronization primitives, not
    shared data.
    """

    id = "RT006"
    name = "cross-thread-race"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ctx.walk():
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        targets = self._thread_targets(cls) & set(methods)
        if not targets:
            return
        calls = {name: self._self_calls(ctx, node) & set(methods)
                 for name, node in methods.items()}
        thread_side = set(targets)
        frontier = list(targets)
        while frontier:
            for callee in calls.get(frontier.pop(), ()):
                if callee not in thread_side:
                    thread_side.add(callee)
                    frontier.append(callee)
        # attr -> side -> {"write": [(node, locked)], "read": [...]}
        access: Dict[str, Dict[str, Dict[str, List]]] = {}
        for name, node in methods.items():
            if name == "__init__":
                continue  # happens-before thread start
            side = "thread" if name in thread_side else "caller"
            for attr, kind, anode, locked in _self_accesses(ctx, node):
                if any(h in attr.lower() for h in _SYNC_HINTS):
                    continue
                access.setdefault(attr, {})[side] = slot = \
                    access.setdefault(attr, {}).get(side,
                                                    {"write": [],
                                                     "read": []})
                slot[kind].append((anode, locked))
        for attr in sorted(access):
            sides = access[attr]
            if "thread" not in sides or "caller" not in sides:
                continue
            for wside, oside in (("thread", "caller"), ("caller", "thread")):
                writes = [n for n, locked in sides[wside]["write"]
                          if not locked]
                others = [n for kind in ("write", "read")
                          for n, locked in sides[oside][kind] if not locked]
                if writes and others:
                    node = min(writes, key=lambda n: n.lineno)
                    yield self.finding(
                        ctx, node,
                        f"`self.{attr}` is written on the "
                        f"{'thread' if wside == 'thread' else 'caller'} "
                        f"side and accessed on the other side of "
                        f"`{cls.name}`'s background thread with no lock "
                        f"in scope on either access — take the class "
                        f"lock (or make it an Event/queue)",
                        token=attr, scope=ctx.scope_of(node))
                    break  # one finding per attribute

    @staticmethod
    def _thread_targets(cls: ast.ClassDef) -> Set[str]:
        targets: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func).rsplit(".", 1)[-1]
            if name != "Thread":
                continue
            for kw in node.keywords:
                if (kw.arg == "target"
                        and isinstance(kw.value, ast.Attribute)
                        and isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "self"):
                    targets.add(kw.value.attr)
        return targets

    @staticmethod
    def _self_calls(ctx: FileContext, method: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in ctx.walk(method):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                out.add(node.func.attr)
        return out


class LockDisciplineRule(Rule):
    """RT010: attribute locked in one method, touched bare in another.

    If any method writes ``self.X`` under ``with self._lock`` (or any
    lock/cond), the class has declared X shared mutable state — so a
    *different* method writing or reading X with no lock in scope is a
    race: it can observe torn multi-field updates, or lose its write
    entirely (the ``dcn_group._accepted`` incident, and PR 12's
    alive-flag, which had to flip under the same lock as the pending-
    faults check). Closures and thread-target bodies nested in a method
    count as that method. ``__init__``/``__del__`` are exempt
    (single-threaded construction/teardown), and so are methods whose
    name ends in ``_locked`` — the repo-wide convention that the CALLER
    holds the lock (the method is only ever invoked from inside a
    ``with self._lock`` block). Attributes named like synchronization
    primitives are skipped. Single-writer designs where a bare read is
    intentionally racy (a stats snapshot, a fast-path hint) should say
    so with a suppression comment.
    """

    id = "RT010"
    name = "lock-discipline"

    _EXEMPT = {"__init__", "__del__", "__enter__", "__exit__"}

    @staticmethod
    def _held_by_contract(name: str) -> bool:
        return name.endswith("_locked")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ctx.walk():
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))
                   and n.name not in self._EXEMPT]
        # attr -> {"locked_writers": {method}, "bare": [(line, node,
        #          method, kind)]}
        table: Dict[str, Dict] = {}
        for m in methods:
            held = self._held_by_contract(m.name)
            for attr, kind, node, locked in _self_accesses(ctx, m):
                if any(h in attr.lower() for h in _SYNC_HINTS):
                    continue
                locked = locked or held
                slot = table.setdefault(attr, {"locked_writers": set(),
                                               "bare": []})
                if locked and kind == "write":
                    slot["locked_writers"].add(m.name)
                elif not locked:
                    slot["bare"].append((node.lineno, node, m.name, kind))
        for attr in sorted(table):
            slot = table[attr]
            if not slot["locked_writers"]:
                continue
            bare = [(ln, nd, meth, kind)
                    for ln, nd, meth, kind in slot["bare"]
                    if meth not in slot["locked_writers"]]
            if not bare:
                continue
            bare.sort(key=lambda t: t[0])
            ln, node, meth, kind = bare[0]
            writers = ", ".join(sorted(slot["locked_writers"]))
            yield self.finding(
                ctx, node,
                f"`self.{attr}` is written under lock in "
                f"`{cls.name}.{writers}` but {'written' if kind == 'write' else 'read'} "
                f"bare here in `{meth}` — the lock's happens-before "
                f"does not cover this access; take the same lock (or "
                f"suppress with the single-writer justification)",
                token=attr, scope=ctx.scope_of(node))
