"""RT004: discarded ObjectRefs."""

from __future__ import annotations

import ast
from typing import Iterator

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule, _dotted


class RefLeakRule(Rule):
    """RT004: ObjectRef created and immediately discarded.

    A bare ``f.remote(...)`` statement creates an ObjectRef nobody will
    ever get() or store: the task's error (if any) is silently dropped,
    and until the ref is GC'd its result pins object-store memory. Store
    the ref, get() it, or — for intentional fire-and-forget — suppress
    with a comment saying so.
    """

    id = "RT004"
    name = "discarded-objectref"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.walk():
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            func = node.value.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr == "remote"):
                continue
            target = (func.value.attr
                      if isinstance(func.value, ast.Attribute)
                      else _dotted(func.value) or "<call>")
            yield self.finding(
                ctx, node,
                f"ObjectRef from `{target}.remote(...)` is discarded — "
                f"its error is silently dropped and its result pins "
                f"store memory until GC; store/get the ref (or suppress "
                f"if fire-and-forget is intended)",
                token=target)
