"""RT011: clock-domain misuse.

The runtime runs two clocks: ``time.time()`` (wall, cross-process
comparable, steps under NTP) and ``time.monotonic()`` /
``time.perf_counter()`` (per-process, duration-safe, meaningless across
processes). The loadgen/latency work (PR 12) was explicit that
perf_counter values are "never differenced against server clocks";
deadline_ts (PR 8) is wall-clock by contract. Mixing domains in one
subtraction produces garbage that *looks* like a duration.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule, _dotted


_WALL_CALLS = {"time.time", "time"}
_MONO_CALLS = {"time.monotonic", "monotonic", "time.perf_counter",
               "perf_counter", "time.monotonic_ns", "monotonic_ns",
               "time.perf_counter_ns", "perf_counter_ns"}

# name-shape fallbacks when we can't see the producing call
# NB: bare "deadline" is NOT a wall hint — repo convention computes
# local deadlines as monotonic() + timeout; only the _ts suffix (the
# PR 8 wire field deadline_ts) marks a wall epoch.
_WALL_HINTS = ("deadline_ts", "_ts", "wall", "epoch_s", "mtime")
_MONO_HINTS = ("mono", "perf", "_t0", "_t1")


def _clock_of_call(node: ast.Call) -> Optional[str]:
    dotted = _dotted(node.func)
    leaf = dotted.rsplit(".", 1)[-1]
    if dotted in _MONO_CALLS or leaf in {"monotonic", "perf_counter",
                                         "monotonic_ns",
                                         "perf_counter_ns"}:
        return "mono"
    if dotted == "time.time" or (leaf == "time"
                                 and dotted.endswith("time.time")):
        return "wall"
    if dotted == "time" or leaf == "time":
        # bare time() — only trust it when the receiver is the module
        if dotted in ("time", "time.time"):
            return "wall"
    return None


def _clock_of_expr(node: ast.AST) -> Optional[str]:
    """Domain of an arbitrary value expression: the domain of the clock
    calls it contains, when they all agree (``monotonic() + timeout`` is
    mono; ``time.time() + budget`` is wall; a mix resolves to nothing)."""
    if isinstance(node, ast.Call):
        d = _clock_of_call(node)
        if d:
            return d
    found = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            d = _clock_of_call(sub)
            if d:
                found.add(d)
    return found.pop() if len(found) == 1 else None


def _name_of(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class ClockDomainRule(Rule):
    """RT011: wall clock differenced against a monotonic clock.

    Tracks, per function, which clock produced each local: an assignment
    from ``time.time()`` is wall; from ``monotonic()``/``perf_counter()``
    is mono. Parameters and attributes fall back to name shape —
    ``deadline_ts``/``*_ts`` are wall by repo convention (PR 8),
    ``*mono*``/``*perf*`` are monotonic. Any ``a - b`` or comparison
    where the two operands provably live in different domains is flagged:
    the result is the offset between two unrelated clocks, not a
    duration, and it drifts with NTP steps. Also flags the inline form
    ``time.time() - monotonic_value`` and deadline checks done against
    the wrong clock. Values that really do bridge domains (a wall epoch
    captured once to stitch cross-process timelines) should carry a
    suppression comment explaining the stitching.
    """

    id = "RT011"
    name = "clock-domain"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        funcs = [n for n in ctx.walk()
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            yield from self._check_function(ctx, fn)

    def _check_function(self, ctx: FileContext, fn) -> Iterator[Finding]:
        domains: Dict[str, str] = {}
        # parameters by name shape
        for a in (fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs):
            d = self._hint_domain(a.arg)
            if d:
                domains[a.arg] = d
        # assignments from clock calls (last-writer-wins, in line order)
        assigns = []
        for node in ctx.walk(fn):
            if isinstance(node, ast.Assign):
                d = _clock_of_expr(node.value)
                if d:
                    for tgt in node.targets:
                        name = _name_of(tgt)
                        if name:
                            assigns.append((node.lineno, name, d))
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None:
                d = _clock_of_expr(node.value)
                name = _name_of(node.target)
                if d and name:
                    assigns.append((node.lineno, name, d))
        for _, name, d in sorted(assigns, key=lambda t: t[0]):
            domains[name] = d

        for node in ctx.walk(fn):
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, ast.Sub):
                yield from self._check_pair(ctx, domains, node,
                                            node.left, node.right,
                                            "differenced")
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.Lt, ast.LtE,
                                                 ast.Gt, ast.GtE)):
                yield from self._check_pair(ctx, domains, node,
                                            node.left,
                                            node.comparators[0],
                                            "compared")

    def _check_pair(self, ctx: FileContext, domains: Dict[str, str],
                    site: ast.AST, left: ast.AST, right: ast.AST,
                    verb: str) -> Iterator[Finding]:
        dl = self._domain_of(domains, left)
        dr = self._domain_of(domains, right)
        if dl and dr and dl != dr:
            wall = left if dl == "wall" else right
            mono = left if dl == "mono" else right
            yield self.finding(
                ctx, site,
                f"wall-clock value `{self._pretty(wall)}` {verb} "
                f"against monotonic value `{self._pretty(mono)}` — the "
                f"result is the offset between two unrelated clocks, "
                f"not a duration, and it moves with NTP steps; keep "
                f"deadlines on time.time() and durations on "
                f"monotonic/perf_counter",
                token="clock-mix")
            return
        # wall-anchor shape: a *direct* time.time() call minus a local
        # whose clock domain is not evident. Durations belong on the
        # monotonic clock; if this is an intentional wall anchor for
        # cross-process stitching, say so with a suppression.
        if verb != "differenced":
            return
        for wall_side, other in ((left, right), (right, left)):
            if (isinstance(wall_side, ast.Call)
                    and _clock_of_call(wall_side) == "wall"
                    and isinstance(other, ast.Name)
                    and self._domain_of(domains, other) is None):
                yield self.finding(
                    ctx, site,
                    f"direct `time.time()` differenced against "
                    f"`{other.id}`, whose clock domain is not evident — "
                    f"if `{other.id}` is a duration or monotonic value "
                    f"this mixes clock domains (use monotonic for "
                    f"durations); if it is a deliberate wall anchor for "
                    f"cross-process stitching, suppress with that "
                    f"rationale",
                    token="wall-anchor")
                return

    def _domain_of(self, domains: Dict[str, str],
                   node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call):
            return _clock_of_call(node)
        name = _name_of(node)
        if name is None:
            return None
        if name in domains:
            return domains[name]
        return self._hint_domain(name)

    @staticmethod
    def _hint_domain(name: str) -> Optional[str]:
        low = name.lower()
        if any(h in low for h in _MONO_HINTS):
            return "mono"
        if any(low.endswith(h) or h in low for h in _WALL_HINTS):
            return "wall"
        return None

    @staticmethod
    def _pretty(node: ast.AST) -> str:
        try:
            return ast.unparse(node)
        except Exception:
            return "<expr>"
