"""RT007: swallowed control-plane exceptions (call-graph-aware)."""

from __future__ import annotations

import ast
from typing import Iterator

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule


class SwallowRule(Rule):
    """RT007: broad except that swallows control-plane errors.

    In serve/train/collective modules, ``except Exception: pass`` (or a
    constant-return/constant-assign body) silently eats
    ``TrainingFailedError``, ``CollectiveTimeoutError``, actor-death
    errors — exactly the signals fault tolerance is built on. v2 is
    call-graph-aware: a helper in any module *reachable from* control-
    plane code is in scope too (``_private/`` runtime internals
    excluded), because its swallow eats the same signals when called
    from serve/train paths. Narrow the type to what the block can
    actually handle, or log at warning with the rank/replica identity
    before falling through.
    """

    id = "RT007"
    name = "swallowed-exception"

    _SCOPES = ("serve/", "train/", "util/collective/")
    _BROAD = {"Exception", "BaseException"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        in_scope = any(s in ctx.path for s in self._SCOPES)
        reach = {}
        if (not in_scope and ctx.project is not None
                and "_private/" not in ctx.path):
            reach = ctx.project.control_reach_quals(ctx.path)
        if not in_scope and not reach:
            return
        for node in ctx.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if not all(self._swallows(stmt) for stmt in node.body):
                continue
            suffix = ""
            if not in_scope:
                fn = ctx.enclosing_function(node)
                qual = ctx.qualname_of(fn) if fn is not None else None
                if qual is None or qual not in reach:
                    continue
                root = reach[qual].split("::", 1)[-1]
                suffix = (f" (this helper is reachable from control-"
                          f"plane code via `{root}`)")
            yield self.finding(
                ctx, node,
                "broad except with a swallow-only body: "
                "TrainingFailedError / CollectiveTimeoutError / actor "
                "death would vanish here — narrow the exception type or "
                "log at warning with the rank/replica identity" + suffix,
                token="swallow")

    @classmethod
    def _is_broad(cls, type_node) -> bool:
        if type_node is None:  # bare except
            return True
        nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        return any(isinstance(n, ast.Name) and n.id in cls._BROAD
                   for n in nodes)

    @staticmethod
    def _swallows(stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            return True
        if isinstance(stmt, ast.Return):
            return stmt.value is None or isinstance(
                stmt.value, (ast.Constant, ast.Name))
        if isinstance(stmt, ast.Assign):
            return isinstance(stmt.value, (ast.Constant, ast.Name,
                                           ast.List, ast.Dict, ast.Set,
                                           ast.Tuple))
        return False
