"""RT005: unfenced collective groups."""

from __future__ import annotations

import ast
from typing import Iterator

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule, _dotted


class CollectiveFenceRule(Rule):
    """RT005: DCN collective group without a gang-epoch fence.

    Collective rings rebuilt after a gang failure MUST be epoch-stamped:
    without ``epoch=``, a zombie rank from the torn-down attempt can
    find the new ring's rendezvous keys and splice into it, corrupting
    every survivor's collective results (PR 2's fault model). Group
    constructors default to epoch=0 — correct only for groups that are
    never rebuilt, which a call site must assert by passing it
    explicitly.
    """

    id = "RT005"
    name = "unfenced-collective"

    _CTORS = {"init_collective_group", "create_collective_group",
              "DcnGroup", "HierarchicalGroup"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func).rsplit(".", 1)[-1]
            if name not in self._CTORS:
                continue
            kwarg_names = {kw.arg for kw in node.keywords}
            if "epoch" in kwarg_names or None in kwarg_names:  # **kwargs
                continue
            yield self.finding(
                ctx, node,
                f"`{name}(...)` without an explicit gang-epoch fence "
                f"(epoch=...): a stale rank from a torn-down gang can "
                f"splice into the rebuilt ring — thread the gang epoch "
                f"through (pass epoch=0 only for never-rebuilt groups)",
                token=name)
