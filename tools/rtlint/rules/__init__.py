"""Rule catalog for rtlint v3.

One module per concern; every rule subclasses :class:`Rule` from
``rules.base`` and is instantiated exactly once here, in id order.
``ALL_RULES`` is the engine's default rule set and the catalog printed
by ``--list-rules``; adding a rule means adding its instance here and a
section to RULES.md (check_claims.py pins the count).
"""

from __future__ import annotations

from typing import List

from tools.rtlint.rules.base import (  # noqa: F401  (re-export for rules)
    Rule,
    _dotted,
    _is_jit_expr,
    _jit_call_sites,
    _traced_bodies,
)
from tools.rtlint.rules.jit import (
    DonatedReuseRule,
    HostSyncRule,
    RetraceRule,
)
from tools.rtlint.rules.blocking import ActorBlockingRule, AsyncBlockingRule
from tools.rtlint.rules.refs import RefLeakRule
from tools.rtlint.rules.collective import CollectiveFenceRule
from tools.rtlint.rules.threads import LockDisciplineRule, ThreadRaceRule
from tools.rtlint.rules.exceptions import SwallowRule
from tools.rtlint.rules.deadline import DeadlineTaintRule
from tools.rtlint.rules.clocks import ClockDomainRule
from tools.rtlint.rules.metrics import MetricsDisciplineRule
from tools.rtlint.rules.lifecycle import (
    BundleLifecycleRule,
    PageLifecycleRule,
    RefLockLifecycleRule,
)
from tools.rtlint.rules.protocol import ProtocolConformanceRule

ALL_RULES: List[Rule] = [
    HostSyncRule(),          # RT001
    RetraceRule(),           # RT002
    ActorBlockingRule(),     # RT003
    RefLeakRule(),           # RT004
    CollectiveFenceRule(),   # RT005
    ThreadRaceRule(),        # RT006
    SwallowRule(),           # RT007
    AsyncBlockingRule(),     # RT008
    DeadlineTaintRule(),     # RT009
    LockDisciplineRule(),    # RT010
    ClockDomainRule(),       # RT011
    DonatedReuseRule(),      # RT012
    MetricsDisciplineRule(),  # RT013
    PageLifecycleRule(),     # RT014
    BundleLifecycleRule(),   # RT015
    RefLockLifecycleRule(),  # RT016
    ProtocolConformanceRule(),  # RT017
]


def rule_by_id(rule_id: str) -> Rule:
    for r in ALL_RULES:
        if r.id == rule_id.upper():
            return r
    raise KeyError(rule_id)
