"""Blocking-call rules: RT003 actor-side gets, RT008 event-loop blocks.

RT003 is the PR 3 actor-deadlock class, now call-graph-aware: helpers
*reachable from* actor methods are in actor context even when they live
in another file. RT008 encodes the CoreClient/serve event-loop class:
a synchronous sleep/socket/get inside an ``async def`` stalls every
coroutine sharing the loop — heartbeats miss, deadlines fire late, and
the whole client looks dead while one handler naps.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule, _dotted, no_timeout


class ActorBlockingRule(Rule):
    """RT003: unbounded blocking get inside an actor method.

    An actor method that calls ``rt.get``/``rt.wait`` (or
    ``response.result()``) with no ``timeout=`` can deadlock the whole
    actor: if the awaited task (transitively) needs *this* actor — or
    its worker died without the GCS noticing yet — the method never
    returns and every queued caller hangs behind it. The same applies
    to control-plane helpers (serve/train/collective modules) and — v2,
    via the project call graph — to any function *reachable from* an
    actor method, whatever file it lives in (``_private/`` runtime
    internals excluded: the core client manages its own deadlines).
    Thread a deadline through (RT_COLLECTIVE_OP_TIMEOUT_S-style
    config), and handle GetTimeoutError.
    """

    id = "RT003"
    name = "actor-blocking-get"

    # Control-plane modules whose free functions execute in actor
    # context (same scoping as RT007).
    _SCOPES = ("serve/", "train/", "util/collective/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        in_control_plane = any(s in ctx.path for s in self._SCOPES)
        seen: set = set()
        for cls in ctx.walk():
            if not isinstance(cls, ast.ClassDef):
                continue
            if not any(self._is_remote_decorator(ctx, d)
                       for d in cls.decorator_list):
                continue
            for node in ctx.walk(cls):
                if not isinstance(node, ast.Call):
                    continue
                op = self._blocking_op(ctx, node)
                if op is None:
                    continue
                seen.add(id(node))
                yield self.finding(
                    ctx, node,
                    f"`{op}` without timeout= inside actor "
                    f"`{cls.name}` — a dead or self-dependent callee "
                    f"deadlocks this actor and everything queued on it; "
                    f"pass a deadline and handle GetTimeoutError",
                    token=op)
        if in_control_plane:
            for node in ctx.walk():
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                op = self._blocking_op(ctx, node)
                if op is None:
                    continue
                seen.add(id(node))
                yield self.finding(
                    ctx, node,
                    f"`{op}` without timeout= in a control-plane module — "
                    f"this helper runs inside actors (collective bootstrap, "
                    f"serve/train plumbing) where an unbounded block "
                    f"deadlocks the caller; pass a deadline and handle "
                    f"GetTimeoutError",
                    token=op)
            return
        # v2: functions reachable from actor methods through the call
        # graph, outside the runtime's own _private/ internals.
        if ctx.project is None or "_private/" in ctx.path:
            return
        reach = ctx.project.actor_reach_quals(ctx.path)
        if not reach:
            return
        for node in ctx.walk():
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            fn = ctx.enclosing_function(node)
            if fn is None:
                continue
            qual = ctx.qualname_of(fn)
            if qual not in reach:
                continue
            op = self._blocking_op(ctx, node)
            if op is None:
                continue
            root = reach[qual].split("::", 1)[-1]
            yield self.finding(
                ctx, node,
                f"`{op}` without timeout= in `{qual}`, which is "
                f"reachable from actor method `{root}` via the call "
                f"graph — an unbounded block there deadlocks the actor; "
                f"pass a deadline and handle GetTimeoutError",
                token=op)

    @staticmethod
    def _is_remote_decorator(ctx: FileContext, dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            dec = dec.func
        if isinstance(dec, ast.Attribute):
            return (dec.attr == "remote" and isinstance(dec.value, ast.Name)
                    and dec.value.id in ctx.rt_aliases)
        if isinstance(dec, ast.Name):
            return (dec.id == "remote"
                    and ctx.from_imports.get(dec.id, "") == "ray_tpu")
        return False

    @staticmethod
    def _blocking_op(ctx: FileContext, call: ast.Call) -> Optional[str]:
        if not no_timeout(call):
            return None
        func = call.func
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            if func.value.id in ctx.rt_aliases and func.attr in {"get",
                                                                 "wait"}:
                return f"rt.{func.attr}"
        if (isinstance(func, ast.Name) and func.id in {"get", "wait"}
                and ctx.from_imports.get(func.id, "") == "ray_tpu"):
            return func.id
        if (isinstance(func, ast.Attribute) and func.attr == "result"
                and not call.args):
            return ".result()"
        return None


class AsyncBlockingRule(Rule):
    """RT008: synchronous blocking call on an event loop.

    ``time.sleep``, socket recv/accept/sendall, ``subprocess.run``,
    unbounded ``rt.get``/``.result()`` or blocking ``queue.get()``
    inside an ``async def`` freezes the whole event loop, not just the
    calling coroutine: on the CoreClient loop that stalls every
    in-flight pull and deadline timer; on the serve loop it stalls every
    request on the replica (the exact head-of-line shape the PR 7
    watchdog measures). Use ``await asyncio.sleep``, loop executors
    (``run_in_executor``/``to_thread``) for truly blocking work, or the
    async variants. v2 is call-graph-aware: a *sync* helper only ever
    called from async context is flagged too, unless it is handed to a
    thread/executor.
    """

    id = "RT008"
    name = "blocking-call-in-async"

    # Popen is included: the fork+exec itself stalls the loop (page-
    # cache misses, audit hooks), and the usual next line is a blocking
    # .wait()/.communicate(). asyncio.create_subprocess_exec is the
    # loop-safe spelling.
    _SUBPROCESS = {"run", "call", "check_output", "check_call", "Popen"}
    _SOCKET_ATTRS = {"recv", "recv_into", "accept", "sendall"}
    _SOCKET_HINTS = ("sock", "conn")
    _QUEUE_HINTS = ("queue", "_q")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        async_quals: Set[str] = set()
        if ctx.project is not None:
            async_quals = ctx.project.async_quals(ctx.path)
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            fn = ctx.enclosing_function(node)
            if fn is None:
                continue
            qual = ctx.qualname_of(fn)
            is_direct = isinstance(fn, ast.AsyncFunctionDef)
            if not is_direct and qual not in async_quals:
                continue
            if self._is_awaited(ctx, node) or self._off_loop(ctx, node):
                continue
            op = self._blocking_op(ctx, node)
            if op is None:
                continue
            where = ("an `async def`" if is_direct else
                     f"`{qual}`, a sync helper only called from async "
                     f"context")
            yield self.finding(
                ctx, node,
                f"`{op}` inside {where} blocks the whole event loop — "
                f"every coroutine sharing it (request handlers, "
                f"deadline timers, heartbeats) stalls; use the await-"
                f"able form or push it to an executor thread",
                token=op)

    @staticmethod
    def _is_awaited(ctx: FileContext, call: ast.Call) -> bool:
        return isinstance(ctx.parent(call), ast.Await)

    @staticmethod
    def _off_loop(ctx: FileContext, call: ast.Call) -> bool:
        """Is this call an *argument* being shipped to an executor
        (run_in_executor(None, f, ...)) rather than invoked here?"""
        parent = ctx.parent(call)
        if isinstance(parent, ast.Call):
            leaf = _dotted(parent.func).rsplit(".", 1)[-1]
            if leaf in {"run_in_executor", "to_thread", "submit",
                        "Thread"}:
                return True
        return False

    def _blocking_op(self, ctx: FileContext,
                     call: ast.Call) -> Optional[str]:
        func = call.func
        dotted = _dotted(func)
        leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
        # time.sleep (or bare sleep imported from time)
        if isinstance(func, ast.Attribute) and func.attr == "sleep" \
                and isinstance(func.value, ast.Name) \
                and func.value.id in ctx.time_aliases:
            return "time.sleep"
        if isinstance(func, ast.Name) and func.id == "sleep" \
                and ctx.from_imports.get("sleep", "") == "time":
            return "sleep"
        # subprocess / os.system
        if dotted in {f"subprocess.{m}" for m in self._SUBPROCESS} \
                or dotted == "os.system":
            return dotted
        # unbounded rt.get / rt.wait / .result()
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            if (func.value.id in ctx.rt_aliases
                    and func.attr in {"get", "wait"}
                    and no_timeout(call)):
                return f"rt.{func.attr}"
        if isinstance(func, ast.Attribute) and func.attr == "result" \
                and not call.args and no_timeout(call):
            return ".result()"
        # socket ops on sock-ish receivers
        if isinstance(func, ast.Attribute) \
                and func.attr in self._SOCKET_ATTRS:
            base = _dotted(func.value).lower()
            if any(h in base for h in self._SOCKET_HINTS):
                return f".{func.attr}()"
        # blocking queue.get() on queue-ish receivers
        if isinstance(func, ast.Attribute) and func.attr == "get" \
                and not call.args and no_timeout(call):
            base = _dotted(func.value).lower()
            if "queue" in base or base.endswith("_q"):
                return ".get()"
        return None
