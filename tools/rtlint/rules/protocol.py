"""RT017: cross-process protocol conformance.

Four consistency checks no single-file rule can do, each encoding a
drift class that ships as a runtime error, not a test failure:

1. **GCS request/response field drift** — every ``_gcs_call("m",
   {...})`` payload is checked against the ``h_m`` handler's required/
   optional keys (from the pass-1 summaries), and every subscript of
   the response against the handler's dict-literal return keys. A
   client missing a required key is a guaranteed ``KeyError`` inside
   the GCS; a response key the handler never returns is a guaranteed
   ``KeyError`` in the client — both only discovered when that RPC
   path finally runs.
2. **Chaos hook table** — ``_private/chaos.py`` documents its
   injection hooks in a module-docstring table; every public hook
   (calls ``_require_enabled``) must appear in the table and every
   table row must name a real module function, so the chaos-suite
   authors' index never rots.
3. **Grafana panel queries** — every metric name referenced by a
   dashboard panel's PromQL ``expr`` must be registered somewhere in
   the project (``Counter``/``Gauge``/``Histogram``/``get_or_create``
   or a synthetic ``{"name": ..., "type": ...}`` series document), so
   renaming a metric cannot silently blank a panel.
4. **Schema-version literals** — readers/writers of versioned
   documents must compare against the shared ``*_VERSION`` constant,
   not a hardcoded int: a bump that forgets a literal-comparing reader
   silently rejects (or accepts) every document.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set

from tools.rtlint.engine import FileContext, Finding
from tools.rtlint.rules.base import Rule

# PromQL functions/keywords/labels that look like metric names.
_PROMQL_STOP = {
    "rate", "irate", "increase", "sum", "avg", "min", "max", "count",
    "by", "without", "on", "ignoring", "le", "quantile", "bottomk",
    "topk", "abs", "ceil", "floor", "round", "delta", "idelta", "label",
    "histogram_quantile", "label_replace", "label_join", "count_values",
    "avg_over_time", "max_over_time", "min_over_time", "sum_over_time",
    "group_left", "group_right", "offset", "bool", "and", "or", "unless",
}
# Series emitted outside the metrics registry (raylet/dashboard text
# exposition) — anything under these prefixes is assumed real.
_SERIES_PREFIX_ALLOW = ("rt_",)
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_VERSION_KEYS = {"schema", "schema_version"}

_NAME_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _handler_map(model) -> Dict[str, Dict]:
    """method name -> handler field info, over the whole project."""
    cached = getattr(model, "_rt017_handlers", None)
    if cached is not None:
        return cached
    out: Dict[str, Dict] = {}
    for s in model.by_path.values():
        for qual, fn in s["defs"].items():
            h = fn.get("gcs_handler")
            if h and fn["name"].startswith("h_"):
                out[fn["name"][2:]] = dict(h, _path=s["path"],
                                           _line=fn["lineno"])
    model._rt017_handlers = out
    return out


def _metric_defs(model) -> Set[str]:
    cached = getattr(model, "_rt017_metrics", None)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for s in model.by_path.values():
        out.update(s.get("metric_defs", ()))
    model._rt017_metrics = out
    return out


def _expr_metric_names(expr: str) -> List[str]:
    """Candidate metric names in one PromQL expression: identifiers
    containing an underscore that are not functions/keywords and not
    label names (inside ``{...}`` selectors or ``by (...)`` clauses)."""
    out: List[str] = []
    depth_brace = 0
    grouping = False
    for m in _NAME_RE.finditer(expr):
        name = m.group(0)
        prefix = expr[:m.start()]
        depth_brace = prefix.count("{") - prefix.count("}")
        if depth_brace > 0:
            continue                       # label matcher
        gm = re.search(r"(?:by|without)\s*\([^)]*$", prefix)
        grouping = gm is not None
        if grouping:
            continue                       # grouping label
        if name in _PROMQL_STOP or "_" not in name:
            continue
        if name not in out:
            out.append(name)
    return out


class ProtocolConformanceRule(Rule):
    """RT017: GCS field drift, chaos-table rot, dashboard/metric drift,
    hardcoded schema versions. See module docstring."""

    id = "RT017"
    name = "protocol-conformance"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._check_gcs_clients(ctx)
        yield from self._check_chaos_table(ctx)
        yield from self._check_panels(ctx)
        yield from self._check_version_literals(ctx)

    # -- 1. GCS client vs handler ----------------------------------------
    def _check_gcs_clients(self, ctx: FileContext) -> Iterator[Finding]:
        model = ctx.project
        if model is None:
            return
        summary = model.by_path.get(ctx.path)
        if summary is None:
            return
        handlers = _handler_map(model)
        if not handlers:
            return
        for qual, fn in summary["defs"].items():
            g = fn.get("gcs") or {}
            for call in g.get("calls", ()):
                method = call["method"]
                h = handlers.get(method)
                node = _line_anchor(ctx, call["lineno"])
                if h is None:
                    yield self.finding(
                        ctx, node,
                        f"`_gcs_call(\"{method}\", ...)` has no matching "
                        f"`h_{method}` handler in the project — typo'd "
                        f"method or handler removed without its callers",
                        token=method, scope=qual)
                    continue
                if not call["literal"] or call["keys"] is None:
                    continue
                keys = set(call["keys"])
                missing = sorted(set(h["required"]) - keys)
                if missing:
                    yield self.finding(
                        ctx, node,
                        f"payload for GCS `{method}` omits key(s) "
                        f"{missing} that the handler reads "
                        f"unconditionally (d[...] at "
                        f"{h['_path']}:{h['_line']}) — guaranteed "
                        f"KeyError inside the GCS",
                        token=f"{method}:missing", scope=qual)
                if not h["req_open"]:
                    unknown = sorted(
                        keys - set(h["required"]) - set(h["optional"]))
                    if unknown:
                        yield self.finding(
                            ctx, node,
                            f"payload for GCS `{method}` sends key(s) "
                            f"{unknown} the handler never reads — stale "
                            f"field or typo (handler at "
                            f"{h['_path']}:{h['_line']})",
                            token=f"{method}:unknown", scope=qual)
            for method, key, lineno in g.get("resp_uses", ()):
                h = handlers.get(method)
                if h is None or h["resp_open"]:
                    continue
                if key not in h["resp"]:
                    yield self.finding(
                        ctx, _line_anchor(ctx, lineno),
                        f"response of GCS `{method}` is subscripted "
                        f"with '{key}' but the handler only returns "
                        f"keys {h['resp']} (handler at "
                        f"{h['_path']}:{h['_line']})",
                        token=f"{method}:{key}", scope=qual)

    # -- 2. chaos docstring table ----------------------------------------
    def _check_chaos_table(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.path.endswith("_private/chaos.py"):
            return
        doc = ast.get_docstring(ctx.tree) or ""
        table: Set[str] = set()
        for line in doc.splitlines():
            m = re.match(r"\s{0,4}([a-z_][a-z0-9_]*)\(.*\|", line)
            if m:
                table.add(m.group(1))
        if not table:
            return
        hooks: Dict[str, ast.AST] = {}
        names: Set[str] = set()
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
                for n in ast.walk(node):
                    if isinstance(n, ast.Call) and isinstance(
                            n.func, ast.Name) \
                            and n.func.id == "_require_enabled":
                        hooks[node.name] = node
                        break
        for name, node in sorted(hooks.items()):
            if name not in table:
                yield self.finding(
                    ctx, node,
                    f"chaos hook `{name}` is gated on RT_CHAOS but "
                    f"missing from the module-docstring injection "
                    f"table — chaos-suite authors index faults there",
                    token=name)
        for name in sorted(table - names):
            yield self.finding(
                ctx, ctx.tree.body[0] if ctx.tree.body else ctx.tree,
                f"injection table documents `{name}()` but no such "
                f"function exists in this module — stale row",
                token=name)

    # -- 3. grafana panels vs metric registrations -----------------------
    def _check_panels(self, ctx: FileContext) -> Iterator[Finding]:
        model = ctx.project
        if model is None:
            return
        summary = model.by_path.get(ctx.path)
        if summary is None or not summary.get("panel_exprs"):
            return
        defined = _metric_defs(model)
        if not defined:
            return
        for expr, lineno in summary["panel_exprs"]:
            for name in _expr_metric_names(expr):
                if name in defined:
                    continue
                base = name
                for suf in _HIST_SUFFIXES + ("_total",):
                    if name.endswith(suf):
                        base = name[:-len(suf)]
                        break
                if base in defined:
                    continue
                if name.startswith(_SERIES_PREFIX_ALLOW):
                    continue
                yield self.finding(
                    ctx, _line_anchor(ctx, lineno),
                    f"panel query references metric `{name}` but no "
                    f"Counter/Gauge/Histogram registration or synthetic "
                    f"series emits it — the panel will render empty",
                    token=name)

    # -- 4. schema-version literals --------------------------------------
    def _check_version_literals(self, ctx: FileContext
                                ) -> Iterator[Finding]:
        for node in ctx.walk():
            # reader: doc.get("schema") ==/!= 2
            if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                key = _version_key_of(node.left)
                other = node.comparators[0]
                if key and isinstance(other, ast.Constant) \
                        and isinstance(other.value, int):
                    yield self.finding(
                        ctx, node,
                        f"'{key}' compared against hardcoded "
                        f"{other.value} — use the shared *_VERSION "
                        f"constant so a schema bump cannot forget "
                        f"this reader",
                        token=key)
            # writer: {"schema": 2, ...}
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) \
                            and k.value in _VERSION_KEYS \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, int):
                        yield self.finding(
                            ctx, v,
                            f"document written with hardcoded "
                            f"'{k.value}': {v.value} — use the shared "
                            f"*_VERSION constant so writer and readers "
                            f"bump together",
                            token=str(k.value))


def _version_key_of(expr: ast.AST) -> Optional[str]:
    """'schema' when `expr` is d.get("schema")/d["schema"]."""
    if isinstance(expr, ast.Call) and isinstance(
            expr.func, ast.Attribute) and expr.func.attr == "get" \
            and expr.args and isinstance(expr.args[0], ast.Constant) \
            and expr.args[0].value in _VERSION_KEYS:
        return expr.args[0].value
    if isinstance(expr, ast.Subscript) and isinstance(
            expr.slice, ast.Constant) \
            and expr.slice.value in _VERSION_KEYS:
        return expr.slice.value
    return None


def _line_anchor(ctx: FileContext, line: int) -> ast.AST:
    for n in ctx.walk():
        if getattr(n, "lineno", None) == line:
            return n
    return ctx.tree
