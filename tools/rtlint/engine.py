"""rtlint core: file context, suppression handling, baseline, runner.

v2 is a two-pass, project-aware analyzer. Pass 1 parses every target
file once and reduces it to a plain-dict summary (tools/rtlint/
project.py); the summaries join into a ``ProjectModel`` — symbol table,
import/re-export resolution, call graph, and the context closures
(traced / async / actor-reachable / control-plane-reachable) the
interprocedural rules consume. Pass 2 runs the rules per file with the
model attached to the ``FileContext``.

Robustness contract: the analyzer never aborts on bad input. A file
that fails to parse, a summarizer crash on exotic code, or a rule
raising mid-walk all degrade to a single RT000 note for that file/rule
and the run continues.

Performance: ``analyze_paths(jobs=N)`` fans pass 1 and pass 2 out over
a process pool, and a content-hash cache (default
``<root>/.rtlint_cache.json``) keyed on (file sha, project digest, rule
signature) makes warm re-runs skip both parsing and rule execution.

Baseline fingerprints are *line-independent* — ``rule|path|scope|token``
— so unrelated edits above a baselined site do not churn the file. Two
identical violations in one scope share a fingerprint; the baseline
stores a count per fingerprint and only a count *increase* is reported.
"""

from __future__ import annotations

import ast
import glob
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools.rtlint.project import (ProjectModel, empty_summary,
                                  module_name_of, summarize_module)

_SUPPRESS_RE = re.compile(r"#\s*rtlint:\s*disable(?:=([A-Za-z0-9_,\s]+))?")

# Engine/summary-shape version: invalidates the whole cache on bump.
# Rule-logic edits are caught automatically by _rulepack_digest(), which
# hashes the linter's own sources into every findings-cache key — before
# that, editing a rule silently served stale findings until the *target*
# file changed.
ENGINE_VERSION = "3.0"

_RULEPACK_DIGEST: Optional[str] = None


def _rulepack_digest() -> str:
    """Content hash of the rule pack itself (every .py under
    tools/rtlint). Memoized per process."""
    global _RULEPACK_DIGEST
    if _RULEPACK_DIGEST is None:
        h = hashlib.sha256()
        pkg = os.path.dirname(os.path.abspath(__file__))
        for dirpath, dirs, files in os.walk(pkg):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for fn in sorted(files):
                if fn.endswith(".py"):
                    h.update(fn.encode())
                    try:
                        with open(os.path.join(dirpath, fn), "rb") as f:
                            h.update(f.read())
                    except OSError:
                        pass
        _RULEPACK_DIGEST = h.hexdigest()[:16]
    return _RULEPACK_DIGEST

# The repo-wide default target set (relative to the lint root): the
# runtime, the tooling (rtlint lints itself), and the root benches.
DEFAULT_TARGETS = ("ray_tpu", "tools", "bench_*.py")


@dataclass
class Finding:
    rule: str          # "RT001"
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str
    scope: str = "<module>"   # enclosing function qualname
    token: str = ""           # short stable detail (call/attr name)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.scope}|{self.token}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "scope": self.scope, "token": self.token,
                "fingerprint": self.fingerprint}

    @classmethod
    def from_dict(cls, d: Dict) -> "Finding":
        return cls(d["rule"], d["path"], d["line"], d["col"],
                   d["message"], d.get("scope", "<module>"),
                   d.get("token", ""))


class FileContext:
    """Everything a rule needs about one parsed file."""

    def __init__(self, source: str, path: str,
                 project: Optional[ProjectModel] = None,
                 tree: Optional[ast.AST] = None):
        self.source = source
        self.path = path.replace(os.sep, "/")
        self.module = module_name_of(self.path)
        self.project = project
        self.lines = source.splitlines()
        self.tree = tree if tree is not None else ast.parse(
            source, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._qualnames: Dict[ast.AST, str] = {}
        # DFS pre-order of every node + subtree spans, captured during
        # the same traversal that builds the parent map: rules re-walk
        # subtrees constantly, and slicing this list is ~10x cheaper
        # than spinning up nested ast.walk generators each time.
        self._order: List[ast.AST] = []
        self._span: Dict[ast.AST, Tuple[int, int]] = {}
        self._link(self.tree, None, prefix="")
        # Module aliases: which local names mean ray_tpu / jax / numpy.
        self.rt_aliases = {"ray_tpu"}
        self.jax_aliases = {"jax"}
        self.np_aliases = {"numpy"}
        self.time_aliases = {"time"}
        self.from_imports: Dict[str, str] = {}  # local name -> module
        self._collect_imports()

    # -- tree plumbing ----------------------------------------------------
    def _link(self, node: ast.AST, parent: Optional[ast.AST], prefix: str):
        if parent is not None:
            self._parents[node] = parent
        start = len(self._order)
        self._order.append(node)
        name = getattr(node, "name", None)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            prefix = f"{prefix}.{name}" if prefix else name
            self._qualnames[node] = prefix
        for child in ast.iter_child_nodes(node):
            self._link(child, node, prefix)
        self._span[node] = (start, len(self._order))

    def walk(self, node: Optional[ast.AST] = None) -> List[ast.AST]:
        """All nodes of `node`'s subtree (default: the whole file) in
        DFS pre-order. Drop-in for ast.walk when visit order does not
        matter; nodes not from this tree fall back to a real walk."""
        if node is None or node is self.tree:
            return self._order
        span = self._span.get(node)
        if span is None:
            return list(ast.walk(node))
        return self._order[span[0]:span[1]]

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def scope_of(self, node: ast.AST) -> str:
        """Qualname of the innermost enclosing function/class."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                return self._qualnames[anc]
        return "<module>"

    def qualname_of(self, node: ast.AST) -> str:
        """Qualname of a def/class node itself."""
        return self._qualnames.get(node, "<module>")

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def in_loop(self, node: ast.AST, within: Optional[ast.AST] = None) -> bool:
        """Is `node` lexically inside a for/while body (not crossing a
        nested function boundary unless that function is `within`)?"""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and anc is not within:
                return False
        return False

    def under_lock(self, node: ast.AST) -> bool:
        """Is `node` inside a ``with <something lock-ish>:`` block?"""
        for anc in self.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if _mentions_lock(item.context_expr):
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False

    # -- imports ----------------------------------------------------------
    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    if a.name.split(".")[0] == "ray_tpu":
                        self.rt_aliases.add(local)
                    elif a.name == "jax" or a.name.startswith("jax."):
                        self.jax_aliases.add(local)
                    elif a.name == "numpy":
                        self.np_aliases.add(local)
                    elif a.name == "time":
                        self.time_aliases.add(local)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = node.module

    def is_module_attr(self, func: ast.AST, aliases: set, attr: str) -> bool:
        """Match ``<alias>.<attr>`` (e.g. rt.get, jax.jit)."""
        return (isinstance(func, ast.Attribute) and func.attr == attr
                and isinstance(func.value, ast.Name)
                and func.value.id in aliases)


def _mentions_lock(expr: ast.AST) -> bool:
    # A Condition ("cond") wraps a lock; `with self._cond:` acquires it.
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name and any(h in name.lower()
                        for h in ("lock", "mutex", "cond")):
            return True
    return False


# -- suppressions ---------------------------------------------------------
def _suppressed_lines(ctx: FileContext) -> Dict[int, Optional[set]]:
    """line -> set of disabled rule ids (None = all rules).

    A ``# rtlint: disable`` comment on a ``def``/``class`` (or decorator)
    line extends over the whole definition body.
    """
    per_line: Dict[int, Optional[set]] = {}
    marked: Dict[int, Optional[set]] = {}
    for i, text in enumerate(ctx.lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = None
        if m.group(1):
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
        marked[i] = rules
        per_line[i] = rules
    if not marked:
        return per_line

    def merge(line: int, rules: Optional[set]):
        cur = per_line.get(line, set())
        if cur is None or rules is None:
            per_line[line] = None
        else:
            per_line[line] = cur | rules

    for node in ctx.walk():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            continue
        head_lines = {node.lineno}
        head_lines.update(d.lineno for d in node.decorator_list)
        for hl in head_lines:
            if hl in marked:
                for line in range(node.lineno, (node.end_lineno or
                                                node.lineno) + 1):
                    merge(line, marked[hl])
    return per_line


def _is_suppressed(finding: Finding,
                   per_line: Dict[int, Optional[set]]) -> bool:
    rules = per_line.get(finding.line, ...)
    if rules is ...:
        return False
    return rules is None or finding.rule in rules


# -- baseline -------------------------------------------------------------
class Baseline:
    """Committed ledger of known findings: fingerprint -> count."""

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("findings", {}))

    def save(self, path: str):
        payload = {
            "comment": ("rtlint baseline: known pre-existing findings "
                        "(fingerprint -> count). Regenerate with "
                        "`python -m tools.rtlint --write-baseline` "
                        "AFTER confirming every new entry is deliberate "
                        "debt, not a new bug."),
            "findings": dict(sorted(self.counts.items())),
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=False)
            f.write("\n")

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        counts: Dict[str, int] = {}
        for fd in findings:
            counts[fd.fingerprint] = counts.get(fd.fingerprint, 0) + 1
        return cls(counts)

    def new_findings(self, findings: Sequence[Finding]) -> List[Finding]:
        """Findings beyond the baselined count per fingerprint (stable
        order: a fingerprint's first N occurrences are absorbed)."""
        seen: Dict[str, int] = {}
        out = []
        for fd in findings:
            seen[fd.fingerprint] = seen.get(fd.fingerprint, 0) + 1
            if seen[fd.fingerprint] > self.counts.get(fd.fingerprint, 0):
                out.append(fd)
        return out

    def stale_entries(self, findings: Sequence[Finding]) -> List[str]:
        """Baselined fingerprints no longer present at all (debt paid —
        candidates for a baseline refresh)."""
        live = {f.fingerprint for f in findings}
        return sorted(k for k in self.counts if k not in live)


# -- per-file lint (pass 2) -----------------------------------------------
def _check_file(ctx: FileContext, rules: Sequence,
                ) -> Tuple[List[Finding], Dict[str, int]]:
    """Run every rule over one parsed file. A rule that raises degrades
    to an RT000 note instead of aborting the run. Returns (unsuppressed
    findings, suppressed-count-per-rule)."""
    per_line = _suppressed_lines(ctx)
    findings: List[Finding] = []
    suppressed: Dict[str, int] = {}
    for rule in rules:
        try:
            rule_findings = list(rule.check(ctx))
        except Exception as e:  # analyzer must degrade, never abort
            findings.append(Finding(
                "RT000", ctx.path, 0, 0,
                f"rule {rule.id} crashed on this file "
                f"({type(e).__name__}: {e}) — findings for it are "
                f"incomplete here", token=f"crash-{rule.id}"))
            continue
        for fd in rule_findings:
            if _is_suppressed(fd, per_line):
                suppressed[fd.rule] = suppressed.get(fd.rule, 0) + 1
            else:
                findings.append(fd)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, suppressed


def lint_source(source: str, path: str,
                rules: Optional[Sequence] = None,
                project: Optional[ProjectModel] = None) -> List[Finding]:
    """Lint one in-memory file; returns unsuppressed findings sorted by
    position. With no `project`, a single-file model is built so the
    interprocedural rules still see intra-file flows. Syntax errors
    yield a single RT000 finding instead of crashing the whole run."""
    from tools.rtlint.rules import ALL_RULES

    norm = path.replace(os.sep, "/")
    try:
        ctx = FileContext(source, path)
    except SyntaxError as e:
        return [Finding("RT000", norm, e.lineno or 0, e.offset or 0,
                        f"syntax error: {e.msg}", token="syntax")]
    if project is None:
        project = ProjectModel([_safe_summary(ctx.tree, norm)])
    ctx.project = project
    findings, _ = _check_file(ctx, rules if rules is not None
                              else ALL_RULES)
    return findings


def _safe_summary(tree: ast.AST, path: str) -> Dict:
    try:
        return summarize_module(tree, path)
    except Exception:
        return empty_summary(path)


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        matches = glob.glob(p) if any(c in p for c in "*?[") else [p]
        for m in sorted(matches):
            if os.path.isfile(m):
                yield m
            else:
                for root, dirs, files in os.walk(m):
                    dirs[:] = sorted(d for d in dirs
                                     if d not in {"__pycache__", ".git"})
                    for fn in sorted(files):
                        if fn.endswith(".py"):
                            yield os.path.join(root, fn)


# -- cache ----------------------------------------------------------------
class _Cache:
    """Content-hash cache: summaries keyed by file sha, findings keyed
    by (file sha, project digest, rule signature)."""

    def __init__(self, path: Optional[str]):
        self.path = path
        # The rule-pack digest is part of the cache version: editing any
        # linter source (rules OR summarizer) invalidates everything.
        # Summaries are keyed only by target-file sha, so without this a
        # summarizer change would silently serve stale pass-1 output.
        version = f"{ENGINE_VERSION}|{_rulepack_digest()}"
        self.data = {"version": version, "summaries": {},
                     "findings": {}}
        self.dirty = False
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    loaded = json.load(f)
                if loaded.get("version") == version:
                    self.data = loaded
            except Exception:
                pass  # corrupt cache == cold cache

    def summary(self, rel: str, sha: str) -> Optional[Dict]:
        ent = self.data["summaries"].get(rel)
        return ent["summary"] if ent and ent["sha"] == sha else None

    def put_summary(self, rel: str, sha: str, summary: Dict):
        self.data["summaries"][rel] = {"sha": sha, "summary": summary}
        self.dirty = True

    def findings(self, rel: str, key: str) -> Optional[Tuple[List, Dict]]:
        ent = self.data["findings"].get(rel)
        if ent and ent["key"] == key:
            return ([Finding.from_dict(d) for d in ent["findings"]],
                    dict(ent["suppressed"]))
        return None

    def put_findings(self, rel: str, key: str,
                     findings: List[Finding], suppressed: Dict):
        self.data["findings"][rel] = {
            "key": key, "findings": [f.to_dict() for f in findings],
            "suppressed": suppressed}
        self.dirty = True

    def save(self):
        if not (self.path and self.dirty):
            return
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.data, f, separators=(",", ":"))
            os.replace(tmp, self.path)
        except Exception:
            pass  # cache is best-effort


def _sha(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8", "replace")).hexdigest()


# -- parallel workers (module-level for picklability) ---------------------
_W: Dict = {}


def _pool_init(root: str, project: Optional[ProjectModel],
               rule_ids: Optional[List[str]]):
    from tools.rtlint.rules import ALL_RULES, rule_by_id
    _W["root"] = root
    _W["project"] = project
    _W["rules"] = (ALL_RULES if rule_ids is None
                   else [rule_by_id(r) for r in rule_ids])


def _p1_worker(rel: str) -> Tuple[str, str, Dict, Optional[Dict]]:
    """Parse + summarize one file. Returns (rel, sha, summary,
    rt000-note-or-None)."""
    fp = os.path.join(_W["root"], rel)
    with open(fp, encoding="utf-8") as f:
        source = f.read()
    sha = _sha(source)
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        note = Finding("RT000", rel.replace(os.sep, "/"), e.lineno or 0,
                       e.offset or 0, f"syntax error: {e.msg}",
                       token="syntax").to_dict()
        return rel, sha, empty_summary(rel.replace(os.sep, "/")), note
    return rel, sha, _safe_summary(tree, rel.replace(os.sep, "/")), None


def _p2_worker(rel: str) -> Tuple[str, List[Dict], Dict[str, int]]:
    fp = os.path.join(_W["root"], rel)
    with open(fp, encoding="utf-8") as f:
        source = f.read()
    try:
        ctx = FileContext(source, rel, project=_W["project"])
    except SyntaxError:
        return rel, [], {}   # already RT000'd in pass 1
    findings, suppressed = _check_file(ctx, _W["rules"])
    return rel, [f.to_dict() for f in findings], suppressed


# -- runner ---------------------------------------------------------------
@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: Dict[str, int] = field(default_factory=dict)
    files: int = 0
    project: Optional[ProjectModel] = None


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Sequence] = None,
                  root: Optional[str] = None,
                  jobs: int = 1,
                  cache_path: Optional[str] = None,
                  only_files: Optional[Sequence[str]] = None,
                  ) -> AnalysisResult:
    """Two-pass analysis over every .py file under `paths`.

    `only_files` (repo-relative) restricts *pass 2* to those files —
    the project model is still built over the full target set, so
    --changed keeps interprocedural context. Finding paths are relative
    to `root` (default: cwd) so fingerprints are machine-independent.
    """
    from tools.rtlint.rules import ALL_RULES

    root = os.path.abspath(root or os.getcwd())
    # More workers than cores only adds fork/pickle overhead — on a
    # 1-core box `--jobs 4` would run *slower* than serial.
    jobs = min(jobs, os.cpu_count() or 1)
    rules = list(rules if rules is not None else ALL_RULES)
    rule_ids = [r.id for r in rules]
    cache = _Cache(cache_path)

    rels: List[str] = []
    for fp in iter_py_files([p if os.path.isabs(p)
                             else os.path.join(root, p) for p in paths]):
        rel = os.path.relpath(os.path.abspath(fp), root)
        if rel not in rels:
            rels.append(rel)

    # ---- pass 1: summaries ----------------------------------------------
    sources: Dict[str, str] = {}
    shas: Dict[str, str] = {}
    summaries: Dict[str, Dict] = {}
    rt000: List[Finding] = []
    trees: Dict[str, ast.AST] = {}
    misses: List[str] = []
    for rel in rels:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            sources[rel] = f.read()
        shas[rel] = _sha(sources[rel])
        hit = cache.summary(rel, shas[rel])
        if hit is not None:
            summaries[rel] = hit
        else:
            misses.append(rel)

    if jobs > 1 and len(misses) > 1:
        import multiprocessing as mp
        with mp.Pool(jobs, initializer=_pool_init,
                     initargs=(root, None, rule_ids)) as pool:
            for rel, sha, summary, note in pool.map(_p1_worker, misses):
                summaries[rel] = summary
                cache.put_summary(rel, sha, summary)
                if note:
                    rt000.append(Finding.from_dict(note))
    else:
        for rel in misses:
            norm = rel.replace(os.sep, "/")
            try:
                tree = ast.parse(sources[rel], filename=rel)
                trees[rel] = tree
                summaries[rel] = _safe_summary(tree, norm)
            except SyntaxError as e:
                rt000.append(Finding("RT000", norm, e.lineno or 0,
                                     e.offset or 0,
                                     f"syntax error: {e.msg}",
                                     token="syntax"))
                summaries[rel] = empty_summary(norm)
            cache.put_summary(rel, shas[rel], summaries[rel])

    try:
        project = ProjectModel([summaries[rel] for rel in rels])
        digest = hashlib.sha256(
            project.digest_src().encode()).hexdigest()[:16]
    except Exception as e:   # model build must never kill the run
        rt000.append(Finding(
            "RT000", "<project>", 0, 0,
            f"project model build failed ({type(e).__name__}: {e}) — "
            f"falling back to per-file analysis", token="model"))
        project = None
        digest = "no-model"

    # ---- pass 2: rules --------------------------------------------------
    lint_rels = (rels if only_files is None
                 else [r for r in rels
                       if r.replace(os.sep, "/") in set(only_files)])
    result = AnalysisResult(project=project, files=len(lint_rels))
    result.findings.extend(f for f in rt000
                           if only_files is None
                           or f.path in set(only_files)
                           or f.path == "<project>")
    key = (f"{digest}|{ENGINE_VERSION}|{_rulepack_digest()}"
           f"|{','.join(rule_ids)}")
    todo: List[str] = []
    for rel in lint_rels:
        hit = cache.findings(rel, f"{shas[rel]}|{key}")
        if hit is not None:
            fs, supp = hit
            result.findings.extend(fs)
            for r, n in supp.items():
                result.suppressed[r] = result.suppressed.get(r, 0) + n
        else:
            todo.append(rel)

    def absorb(rel: str, findings: List[Finding], suppressed: Dict):
        cache.put_findings(rel, f"{shas[rel]}|{key}", findings,
                           suppressed)
        result.findings.extend(findings)
        for r, n in suppressed.items():
            result.suppressed[r] = result.suppressed.get(r, 0) + n

    if jobs > 1 and len(todo) > 1 and project is not None:
        import multiprocessing as mp
        with mp.Pool(jobs, initializer=_pool_init,
                     initargs=(root, project, rule_ids)) as pool:
            for rel, fdicts, suppressed in pool.map(_p2_worker, todo):
                absorb(rel, [Finding.from_dict(d) for d in fdicts],
                       suppressed)
    else:
        for rel in todo:
            try:
                ctx = FileContext(sources[rel], rel, project=project,
                                  tree=trees.get(rel))
            except SyntaxError:
                continue  # RT000 already recorded in pass 1
            findings, suppressed = _check_file(ctx, rules)
            absorb(rel, findings, suppressed)

    cache.save()
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result


def lint_paths(paths: Sequence[str], rules: Optional[Sequence] = None,
               root: Optional[str] = None) -> List[Finding]:
    """Lint every .py file under `paths` (back-compat wrapper around
    analyze_paths); finding paths are relative to `root` (default: cwd)
    so fingerprints are machine-independent."""
    return analyze_paths(paths, rules=rules, root=root).findings
