"""rtlint core: file context, suppression handling, baseline, runner.

The engine is rule-agnostic: it parses each file once, builds the shared
analysis context (parent links, import aliases, qualified scope names),
applies every rule, then drops findings that are suppressed inline or
absorbed by the committed baseline.

Baseline fingerprints are *line-independent* — ``rule|path|scope|token``
— so unrelated edits above a baselined site do not churn the file. Two
identical violations in one scope share a fingerprint; the baseline
stores a count per fingerprint and only a count *increase* is reported.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

_SUPPRESS_RE = re.compile(r"#\s*rtlint:\s*disable(?:=([A-Za-z0-9_,\s]+))?")


@dataclass
class Finding:
    rule: str          # "RT001"
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str
    scope: str = "<module>"   # enclosing function qualname
    token: str = ""           # short stable detail (call/attr name)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.scope}|{self.token}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")


class FileContext:
    """Everything a rule needs about one parsed file."""

    def __init__(self, source: str, path: str):
        self.source = source
        self.path = path.replace(os.sep, "/")
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._qualnames: Dict[ast.AST, str] = {}
        self._link(self.tree, None, prefix="")
        # Module aliases: which local names mean ray_tpu / jax / numpy.
        self.rt_aliases = {"ray_tpu"}
        self.jax_aliases = {"jax"}
        self.np_aliases = {"numpy"}
        self.from_imports: Dict[str, str] = {}  # local name -> module
        self._collect_imports()

    # -- tree plumbing ----------------------------------------------------
    def _link(self, node: ast.AST, parent: Optional[ast.AST], prefix: str):
        if parent is not None:
            self._parents[node] = parent
        name = getattr(node, "name", None)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            prefix = f"{prefix}.{name}" if prefix else name
            self._qualnames[node] = prefix
        for child in ast.iter_child_nodes(node):
            self._link(child, node, prefix)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def scope_of(self, node: ast.AST) -> str:
        """Qualname of the innermost enclosing function/class."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                return self._qualnames[anc]
        return "<module>"

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def in_loop(self, node: ast.AST, within: Optional[ast.AST] = None) -> bool:
        """Is `node` lexically inside a for/while body (not crossing a
        nested function boundary unless that function is `within`)?"""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and anc is not within:
                return False
        return False

    def under_lock(self, node: ast.AST) -> bool:
        """Is `node` inside a ``with <something lock-ish>:`` block?"""
        for anc in self.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if _mentions_lock(item.context_expr):
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False

    # -- imports ----------------------------------------------------------
    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    if a.name.split(".")[0] == "ray_tpu":
                        self.rt_aliases.add(local)
                    elif a.name == "jax" or a.name.startswith("jax."):
                        self.jax_aliases.add(local)
                    elif a.name == "numpy":
                        self.np_aliases.add(local)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = node.module

    def is_module_attr(self, func: ast.AST, aliases: set, attr: str) -> bool:
        """Match ``<alias>.<attr>`` (e.g. rt.get, jax.jit)."""
        return (isinstance(func, ast.Attribute) and func.attr == attr
                and isinstance(func.value, ast.Name)
                and func.value.id in aliases)


def _mentions_lock(expr: ast.AST) -> bool:
    # A Condition ("cond") wraps a lock; `with self._cond:` acquires it.
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name and any(h in name.lower()
                        for h in ("lock", "mutex", "cond")):
            return True
    return False


# -- suppressions ---------------------------------------------------------
def _suppressed_lines(ctx: FileContext) -> Dict[int, Optional[set]]:
    """line -> set of disabled rule ids (None = all rules).

    A ``# rtlint: disable`` comment on a ``def``/``class`` (or decorator)
    line extends over the whole definition body.
    """
    per_line: Dict[int, Optional[set]] = {}
    marked: Dict[int, Optional[set]] = {}
    for i, text in enumerate(ctx.lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = None
        if m.group(1):
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
        marked[i] = rules
        per_line[i] = rules
    if not marked:
        return per_line

    def merge(line: int, rules: Optional[set]):
        cur = per_line.get(line, set())
        if cur is None or rules is None:
            per_line[line] = None
        else:
            per_line[line] = cur | rules

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            continue
        head_lines = {node.lineno}
        head_lines.update(d.lineno for d in node.decorator_list)
        for hl in head_lines:
            if hl in marked:
                for line in range(node.lineno, (node.end_lineno or
                                                node.lineno) + 1):
                    merge(line, marked[hl])
    return per_line


def _is_suppressed(finding: Finding,
                   per_line: Dict[int, Optional[set]]) -> bool:
    rules = per_line.get(finding.line, ...)
    if rules is ...:
        return False
    return rules is None or finding.rule in rules


# -- baseline -------------------------------------------------------------
class Baseline:
    """Committed ledger of known findings: fingerprint -> count."""

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("findings", {}))

    def save(self, path: str):
        payload = {
            "comment": ("rtlint baseline: known pre-existing findings "
                        "(fingerprint -> count). Regenerate with "
                        "`python -m tools.rtlint --write-baseline ray_tpu/` "
                        "AFTER confirming every new entry is deliberate "
                        "debt, not a new bug."),
            "findings": dict(sorted(self.counts.items())),
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=False)
            f.write("\n")

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        counts: Dict[str, int] = {}
        for fd in findings:
            counts[fd.fingerprint] = counts.get(fd.fingerprint, 0) + 1
        return cls(counts)

    def new_findings(self, findings: Sequence[Finding]) -> List[Finding]:
        """Findings beyond the baselined count per fingerprint (stable
        order: a fingerprint's first N occurrences are absorbed)."""
        seen: Dict[str, int] = {}
        out = []
        for fd in findings:
            seen[fd.fingerprint] = seen.get(fd.fingerprint, 0) + 1
            if seen[fd.fingerprint] > self.counts.get(fd.fingerprint, 0):
                out.append(fd)
        return out

    def stale_entries(self, findings: Sequence[Finding]) -> List[str]:
        """Baselined fingerprints no longer present at all (debt paid —
        candidates for a baseline refresh)."""
        live = {f.fingerprint for f in findings}
        return sorted(k for k in self.counts if k not in live)


# -- runner ---------------------------------------------------------------
def lint_source(source: str, path: str,
                rules: Optional[Sequence] = None) -> List[Finding]:
    """Lint one in-memory file; returns unsuppressed findings sorted by
    position. Syntax errors yield a single RT000 finding instead of
    crashing the whole run."""
    from tools.rtlint.rules import ALL_RULES

    try:
        ctx = FileContext(source, path)
    except SyntaxError as e:
        return [Finding("RT000", path.replace(os.sep, "/"),
                        e.lineno or 0, e.offset or 0,
                        f"syntax error: {e.msg}", token="syntax")]
    per_line = _suppressed_lines(ctx)
    findings: List[Finding] = []
    for rule in (rules if rules is not None else ALL_RULES):
        for fd in rule.check(ctx):
            if not _is_suppressed(fd, per_line):
                findings.append(fd)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in {"__pycache__", ".git"})
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)


def lint_paths(paths: Sequence[str], rules: Optional[Sequence] = None,
               root: Optional[str] = None) -> List[Finding]:
    """Lint every .py file under `paths`; finding paths are relative to
    `root` (default: cwd) so fingerprints are machine-independent."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    for fp in iter_py_files(paths):
        with open(fp, encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(os.path.abspath(fp), root)
        findings.extend(lint_source(source, rel, rules))
    return findings
