"""rtlint rules RT001–RT007.

Each rule is motivated by a bug class this repo has actually shipped and
later fixed (see RULES.md for the incident references). Rules are
deliberately *syntactic*: they over-approximate, and intentional
violations carry an inline ``# rtlint: disable=RTxxx`` with a comment
explaining why the pattern is safe there — which doubles as
documentation at the call site.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.rtlint.engine import FileContext, Finding

# Names that mean "this code runs under jax.jit tracing".
_JIT_NAMES = {"jit", "pjit"}
# Host-sync operations: each forces (or implies) a device->host transfer
# the TPU pipeline must drain for.
_SYNC_ATTRS = {"item", "block_until_ready", "copy_to_host"}
_NP_CONVERTERS = {"asarray", "array"}


class Rule:
    id: str = ""
    name: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str,
                token: str, scope: Optional[str] = None) -> Finding:
        return Finding(
            self.id, ctx.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), message,
            scope=scope if scope is not None else ctx.scope_of(node),
            token=token,
        )


# -- shared jit detection -------------------------------------------------
def _dotted(func: ast.AST) -> str:
    """Best-effort dotted name of a call target ('jax.jit', 'rt.get')."""
    parts: List[str] = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def _is_jit_expr(ctx: FileContext, node: ast.AST) -> bool:
    """Does this expression denote jax.jit / jit / pjit (possibly through
    functools.partial)?"""
    if isinstance(node, ast.Name):
        return (node.id in _JIT_NAMES
                and ctx.from_imports.get(node.id, "").startswith("jax"))
    if isinstance(node, ast.Attribute):
        return (node.attr in _JIT_NAMES
                and isinstance(node.value, ast.Name)
                and node.value.id in ctx.jax_aliases)
    if isinstance(node, ast.Call):
        if _is_jit_expr(ctx, node.func):
            return True
        # functools.partial(jax.jit, ...) — the partial IS a jit wrapper.
        if _dotted(node.func) in {"partial", "functools.partial"}:
            return any(_is_jit_expr(ctx, a) for a in node.args)
    return False


def _jit_call_sites(ctx: FileContext) -> Iterator[ast.Call]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_jit_expr(ctx, node.func):
            yield node


def _traced_bodies(ctx: FileContext) -> List[ast.AST]:
    """Function/lambda nodes whose bodies run under jit tracing: defs
    decorated with jit, and callables passed directly to a jit call."""
    traced: List[ast.AST] = []
    local_defs: Dict[Tuple[str, str], ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs[(ctx.scope_of(node), node.name)] = node
            if any(_is_jit_expr(ctx, d) for d in node.decorator_list):
                traced.append(node)
    for call in _jit_call_sites(ctx):
        if not call.args:
            continue
        fn = call.args[0]
        if isinstance(fn, ast.Lambda):
            traced.append(fn)
        elif isinstance(fn, ast.Name):
            target = local_defs.get((ctx.scope_of(call), fn.id))
            if target is not None:
                traced.append(target)
    return traced


# -- RT001 ----------------------------------------------------------------
class HostSyncRule(Rule):
    """RT001: device->host sync reachable from traced or hot-loop code.

    Inside a jit-traced function, ``.item()`` / ``float()`` / ``int()``
    on arrays, ``np.asarray``, ``jax.device_get`` and
    ``block_until_ready`` either fail at trace time or silently force a
    sync on every call. Outside traced code, the same syncs inside a
    ``for``/``while`` body are the per-step host round trips that made
    the serving engine 27x slower than its raw decode floor (PR 1).
    """

    id = "RT001"
    name = "host-sync-in-hot-path"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        traced = _traced_bodies(ctx)
        traced_nodes: Set[int] = set()
        for t in traced:
            for node in ast.walk(t):
                traced_nodes.add(id(node))

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            op = self._sync_op(ctx, node, in_traced=id(node) in traced_nodes)
            if op is None:
                continue
            if id(node) in traced_nodes:
                yield self.finding(
                    ctx, node,
                    f"`{op}` inside a jit-traced function forces a "
                    f"device->host sync (or fails at trace time); hoist "
                    f"it out of the traced body",
                    token=op)
            elif ctx.in_loop(node):
                yield self.finding(
                    ctx, node,
                    f"`{op}` inside a loop body syncs host<->device every "
                    f"iteration — batch it, move it off-step, or fetch "
                    f"async (copy_to_host_async) and drain once",
                    token=op)

    @staticmethod
    def _sync_op(ctx: FileContext, call: ast.Call,
                 in_traced: bool) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in _SYNC_ATTRS:
                return f".{func.attr}()"
            if (isinstance(func.value, ast.Name)
                    and func.value.id in ctx.jax_aliases
                    and func.attr in {"device_get", "block_until_ready"}):
                return f"jax.{func.attr}"
            # np.asarray/np.array only matter under tracing (outside,
            # numpy conversions in loops are ordinary host code).
            if (in_traced and isinstance(func.value, ast.Name)
                    and func.value.id in ctx.np_aliases
                    and func.attr in _NP_CONVERTERS):
                return f"np.{func.attr}"
        elif (in_traced and isinstance(func, ast.Name)
                and func.id in {"float", "int", "bool"}
                and len(call.args) == 1
                and not isinstance(call.args[0], ast.Constant)):
            return f"{func.id}()"
        return None


# -- RT002 ----------------------------------------------------------------
class RetraceRule(Rule):
    """RT002: jit retrace risk.

    ``jax.jit(...)`` evaluated inside a loop body builds a *fresh*
    compiled-function cache every iteration — every call recompiles
    (this, not the math, was most of the serving engine's original 27x
    gap). A ``@jit`` decorator on a def nested in a loop is the same bug.
    A mutable (list/set/dict) ``static_argnums``/``static_argnames``
    spec can be mutated between calls, changing the cache key and
    silently retracing.
    """

    id = "RT002"
    name = "retrace-risk"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for call in _jit_call_sites(ctx):
            if ctx.in_loop(call):
                yield self.finding(
                    ctx, call,
                    "jax.jit called inside a loop body: each iteration "
                    "builds a fresh jit wrapper with an empty cache, so "
                    "every call recompiles — hoist the jit out of the "
                    "loop",
                    token="jit-in-loop")
            for kw in call.keywords:
                if (kw.arg in {"static_argnums", "static_argnames"}
                        and isinstance(kw.value,
                                       (ast.List, ast.Set, ast.Dict))):
                    yield self.finding(
                        ctx, kw.value,
                        f"{kw.arg} given a mutable {type(kw.value).__name__.lower()} "
                        f"literal — mutation between calls changes the "
                        f"cache key and silently retraces; pass a tuple",
                        token=f"static-{kw.arg}")
        for node in ast.walk(ctx.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and ctx.in_loop(node)
                    and any(_is_jit_expr(ctx, d)
                            for d in node.decorator_list)):
                yield self.finding(
                    ctx, node,
                    f"@jit-decorated def `{node.name}` inside a loop body "
                    f"re-wraps (and re-traces) every iteration — define "
                    f"it once outside the loop",
                    token="jit-def-in-loop")


# -- RT003 ----------------------------------------------------------------
class ActorBlockingRule(Rule):
    """RT003: unbounded blocking get inside an actor method.

    An actor method that calls ``rt.get``/``rt.wait`` (or
    ``response.result()``) with no ``timeout=`` can deadlock the whole
    actor: if the awaited task (transitively) needs *this* actor — or
    its worker died without the GCS noticing yet — the method never
    returns and every queued caller hangs behind it. The same applies
    to control-plane helpers (serve/train/collective modules) that run
    *inside* actors even though they aren't methods of one — e.g. the
    collective bootstrap. Thread a deadline through
    (RT_COLLECTIVE_OP_TIMEOUT_S-style config), and handle
    GetTimeoutError.
    """

    id = "RT003"
    name = "actor-blocking-get"

    # Control-plane modules whose free functions execute in actor
    # context (same scoping as RT007).
    _SCOPES = ("serve/", "train/", "util/collective/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        in_control_plane = any(s in ctx.path for s in self._SCOPES)
        seen: set = set()
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not any(self._is_remote_decorator(ctx, d)
                       for d in cls.decorator_list):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.Call):
                    continue
                op = self._blocking_op(ctx, node)
                if op is None:
                    continue
                seen.add(id(node))
                yield self.finding(
                    ctx, node,
                    f"`{op}` without timeout= inside actor "
                    f"`{cls.name}` — a dead or self-dependent callee "
                    f"deadlocks this actor and everything queued on it; "
                    f"pass a deadline and handle GetTimeoutError",
                    token=op)
        if not in_control_plane:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            op = self._blocking_op(ctx, node)
            if op is None:
                continue
            yield self.finding(
                ctx, node,
                f"`{op}` without timeout= in a control-plane module — "
                f"this helper runs inside actors (collective bootstrap, "
                f"serve/train plumbing) where an unbounded block "
                f"deadlocks the caller; pass a deadline and handle "
                f"GetTimeoutError",
                token=op)

    @staticmethod
    def _is_remote_decorator(ctx: FileContext, dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            dec = dec.func
        if isinstance(dec, ast.Attribute):
            return (dec.attr == "remote" and isinstance(dec.value, ast.Name)
                    and dec.value.id in ctx.rt_aliases)
        if isinstance(dec, ast.Name):
            return (dec.id == "remote"
                    and ctx.from_imports.get(dec.id, "") == "ray_tpu")
        return False

    @staticmethod
    def _blocking_op(ctx: FileContext, call: ast.Call) -> Optional[str]:
        kwarg_names = {kw.arg for kw in call.keywords}
        if "timeout" in kwarg_names or None in kwarg_names:  # **kwargs
            return None
        func = call.func
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            if func.value.id in ctx.rt_aliases and func.attr in {"get",
                                                                 "wait"}:
                return f"rt.{func.attr}"
        if (isinstance(func, ast.Name) and func.id in {"get", "wait"}
                and ctx.from_imports.get(func.id, "") == "ray_tpu"):
            return func.id
        if (isinstance(func, ast.Attribute) and func.attr == "result"
                and not call.args):
            return ".result()"
        return None


# -- RT004 ----------------------------------------------------------------
class RefLeakRule(Rule):
    """RT004: ObjectRef created and immediately discarded.

    A bare ``f.remote(...)`` statement creates an ObjectRef nobody will
    ever get() or store: the task's error (if any) is silently dropped,
    and until the ref is GC'd its result pins object-store memory. Store
    the ref, get() it, or — for intentional fire-and-forget — suppress
    with a comment saying so.
    """

    id = "RT004"
    name = "discarded-objectref"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            func = node.value.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr == "remote"):
                continue
            target = (func.value.attr
                      if isinstance(func.value, ast.Attribute)
                      else _dotted(func.value) or "<call>")
            yield self.finding(
                ctx, node,
                f"ObjectRef from `{target}.remote(...)` is discarded — "
                f"its error is silently dropped and its result pins "
                f"store memory until GC; store/get the ref (or suppress "
                f"if fire-and-forget is intended)",
                token=target)


# -- RT005 ----------------------------------------------------------------
class CollectiveFenceRule(Rule):
    """RT005: DCN collective group without a gang-epoch fence.

    Collective rings rebuilt after a gang failure MUST be epoch-stamped:
    without ``epoch=``, a zombie rank from the torn-down attempt can
    find the new ring's rendezvous keys and splice into it, corrupting
    every survivor's collective results (PR 2's fault model). Group
    constructors default to epoch=0 — correct only for groups that are
    never rebuilt, which a call site must assert by passing it
    explicitly.
    """

    id = "RT005"
    name = "unfenced-collective"

    _CTORS = {"init_collective_group", "create_collective_group",
              "DcnGroup", "HierarchicalGroup"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func).rsplit(".", 1)[-1]
            if name not in self._CTORS:
                continue
            kwarg_names = {kw.arg for kw in node.keywords}
            if "epoch" in kwarg_names or None in kwarg_names:  # **kwargs
                continue
            yield self.finding(
                ctx, node,
                f"`{name}(...)` without an explicit gang-epoch fence "
                f"(epoch=...): a stale rank from a torn-down gang can "
                f"splice into the rebuilt ring — thread the gang epoch "
                f"through (pass epoch=0 only for never-rebuilt groups)",
                token=name)


# -- RT006 ----------------------------------------------------------------
class ThreadRaceRule(Rule):
    """RT006: unlocked cross-thread attribute access.

    For every class that starts a ``threading.Thread`` on one of its own
    methods, partition methods into thread-side (the target and
    everything it transitively calls on self) and caller-side. An
    attribute *written* without a lock on one side and *accessed*
    without a lock on the other is a data race candidate. ``__init__``
    writes are exempt (they happen-before the thread start); attributes
    whose names say lock/event/cond are synchronization primitives, not
    shared data.
    """

    id = "RT006"
    name = "cross-thread-race"

    _SYNC_HINTS = ("lock", "event", "cond", "sem", "mutex")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        targets = self._thread_targets(cls) & set(methods)
        if not targets:
            return
        calls = {name: self._self_calls(node) & set(methods)
                 for name, node in methods.items()}
        thread_side = set(targets)
        frontier = list(targets)
        while frontier:
            for callee in calls.get(frontier.pop(), ()):
                if callee not in thread_side:
                    thread_side.add(callee)
                    frontier.append(callee)
        # attr -> side -> {"write": [(node, locked)], "read": [...]}
        access: Dict[str, Dict[str, Dict[str, List]]] = {}
        for name, node in methods.items():
            if name == "__init__":
                continue  # happens-before thread start
            side = "thread" if name in thread_side else "caller"
            for attr, kind, anode, locked in self._self_accesses(ctx, node):
                if any(h in attr.lower() for h in self._SYNC_HINTS):
                    continue
                access.setdefault(attr, {})[side] = slot = \
                    access.setdefault(attr, {}).get(side,
                                                    {"write": [],
                                                     "read": []})
                slot[kind].append((anode, locked))
        for attr in sorted(access):
            sides = access[attr]
            if "thread" not in sides or "caller" not in sides:
                continue
            for wside, oside in (("thread", "caller"), ("caller", "thread")):
                writes = [n for n, locked in sides[wside]["write"]
                          if not locked]
                others = [n for kind in ("write", "read")
                          for n, locked in sides[oside][kind] if not locked]
                if writes and others:
                    node = min(writes, key=lambda n: n.lineno)
                    yield self.finding(
                        ctx, node,
                        f"`self.{attr}` is written on the "
                        f"{'thread' if wside == 'thread' else 'caller'} "
                        f"side and accessed on the other side of "
                        f"`{cls.name}`'s background thread with no lock "
                        f"in scope on either access — take the class "
                        f"lock (or make it an Event/queue)",
                        token=attr, scope=ctx.scope_of(node))
                    break  # one finding per attribute

    @staticmethod
    def _thread_targets(cls: ast.ClassDef) -> Set[str]:
        targets: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func).rsplit(".", 1)[-1]
            if name != "Thread":
                continue
            for kw in node.keywords:
                if (kw.arg == "target"
                        and isinstance(kw.value, ast.Attribute)
                        and isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "self"):
                    targets.add(kw.value.attr)
        return targets

    @staticmethod
    def _self_calls(method: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(method):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                out.add(node.func.attr)
        return out

    @staticmethod
    def _self_accesses(ctx: FileContext, method: ast.AST):
        """Yields (attr, 'read'|'write', node, locked) for self.X uses.
        A subscript/augmented store through self.X counts as a write of
        X's contents."""
        for node in ast.walk(method):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            kind = "read"
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                kind = "write"
            else:
                parent = ctx.parent(node)
                if (isinstance(parent, ast.Subscript)
                        and isinstance(parent.ctx, (ast.Store, ast.Del))):
                    kind = "write"
                elif isinstance(parent, ast.AugAssign) and \
                        parent.target is node:
                    kind = "write"
            yield node.attr, kind, node, ctx.under_lock(node)


# -- RT007 ----------------------------------------------------------------
class SwallowRule(Rule):
    """RT007: broad except that swallows control-plane errors.

    In serve/train/collective modules, ``except Exception: pass`` (or a
    constant-return/constant-assign body) silently eats
    ``TrainingFailedError``, ``CollectiveTimeoutError``, actor-death
    errors — exactly the signals fault tolerance is built on. Narrow
    the type to what the block can actually handle, or log at warning
    with the rank/replica identity before falling through.
    """

    id = "RT007"
    name = "swallowed-exception"

    _SCOPES = ("serve/", "train/", "util/collective/")
    _BROAD = {"Exception", "BaseException"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not any(s in ctx.path for s in self._SCOPES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if not all(self._swallows(stmt) for stmt in node.body):
                continue
            yield self.finding(
                ctx, node,
                "broad except with a swallow-only body: "
                "TrainingFailedError / CollectiveTimeoutError / actor "
                "death would vanish here — narrow the exception type or "
                "log at warning with the rank/replica identity",
                token="swallow")

    @classmethod
    def _is_broad(cls, type_node) -> bool:
        if type_node is None:  # bare except
            return True
        nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        return any(isinstance(n, ast.Name) and n.id in cls._BROAD
                   for n in nodes)

    @staticmethod
    def _swallows(stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            return True
        if isinstance(stmt, ast.Return):
            return stmt.value is None or isinstance(
                stmt.value, (ast.Constant, ast.Name))
        if isinstance(stmt, ast.Assign):
            return isinstance(stmt.value, (ast.Constant, ast.Name,
                                           ast.List, ast.Dict, ast.Set,
                                           ast.Tuple))
        return False


ALL_RULES: List[Rule] = [
    HostSyncRule(),
    RetraceRule(),
    ActorBlockingRule(),
    RefLeakRule(),
    CollectiveFenceRule(),
    ThreadRaceRule(),
    SwallowRule(),
]


def rule_by_id(rule_id: str) -> Rule:
    for r in ALL_RULES:
        if r.id == rule_id.upper():
            return r
    raise KeyError(rule_id)
