"""Doc-vs-artifact claim checker.

Perf numbers quoted in README.md / COMPONENTS.md drift from the
committed JSON artifacts as rounds iterate (flagged in two consecutive
verdicts) — and one stale number means a reader can trust none of them.
This tool pins every quoted number to its artifact: each CLAIM names a
doc file, a regex whose group(1) captures the quoted value, a getter
into the artifact JSON, and a tolerance. The test suite runs it
(test_bench_harness.py), so a doc edit that outruns its artifact — or a
regenerated artifact that outruns the docs — fails CI.

Run directly for a report:  python tools/check_claims.py
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Callable, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name: str):
    with open(os.path.join(REPO, name)) as f:
        return json.load(f)


def _bench_core(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_CORE.json"):
            if metric_sub in e.get("benchmark", ""):
                return e[field]
        raise KeyError(f"no BENCH_CORE entry matching {metric_sub!r}")
    return get


def _bench_scale_broadcast(nodes: int, field: str):
    def get():
        for e in _load("BENCH_SCALE.json"):
            if e.get("probe", "").endswith(f"broadcast to {nodes} nodes"):
                return e[field]
        raise KeyError(f"no broadcast-to-{nodes} probe in BENCH_SCALE.json")
    return get


def _bench_scale_tasks(n: int, field: str):
    def get():
        for e in _load("BENCH_SCALE.json"):
            if e.get("probe") == "cost_curves":
                for pt in e["tasks"]:
                    if pt["n"] == n:
                        return pt[field]
        raise KeyError(f"no tasks curve point n={n} in BENCH_SCALE.json")
    return get


def _bench_scale_probe(probe: str, field: str):
    def get():
        for e in _load("BENCH_SCALE.json"):
            if e.get("probe") == probe:
                return e[field]
        raise KeyError(f"no probe {probe!r} in BENCH_SCALE.json")
    return get


def _bench_scale_lifecycle(n: int, field: str, phase: str = None):
    """Lifecycle decomposition point n=<n>: a top-level field, or one
    phase's mean µs when ``phase`` is given."""
    def get():
        for e in _load("BENCH_SCALE.json"):
            if e.get("probe") == "lifecycle phase decomposition":
                for pt in e["points"]:
                    if pt["n"] == n:
                        return pt["phases_us"][phase] if phase else pt[field]
        raise KeyError(
            f"no lifecycle decomposition point n={n} in BENCH_SCALE.json"
        )
    return get


def _bench_infer(metric_sub: str, field: str, **where):
    def get():
        for e in _load("BENCH_INFER.json"):
            if metric_sub in e.get("metric", "") and all(
                e.get(k) == v for k, v in where.items()
            ):
                return e[field]
        raise KeyError(
            f"no BENCH_INFER entry matching {metric_sub!r} {where}"
        )
    return get


def _bench_infer_r5_implied_step_ms():
    """The r5 TPU continuous-batching probe ran 4 slots; its implied
    steady-state engine step is slots / throughput."""
    def get():
        tps = _bench_infer("continuous batching tokens/s/chip",
                           "continuous_tokens_per_s")()
        return 4.0 / tps * 1e3
    return get


def _bench_data(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_DATA.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(f"no BENCH_DATA entry matching {metric_sub!r}")
    return get


def _bench_obs(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_OBS.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(f"no BENCH_OBS entry matching {metric_sub!r}")
    return get


def _bench_serve_obs(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_SERVE_OBS.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(f"no BENCH_SERVE_OBS entry matching {metric_sub!r}")
    return get


def _bench_ft(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_FT.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(f"no BENCH_FT entry matching {metric_sub!r}")
    return get


def _bench_serve_ft(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_SERVE_FT.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(f"no BENCH_SERVE_FT entry matching {metric_sub!r}")
    return get


def _bench_multitenant(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_MULTITENANT.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(
            f"no BENCH_MULTITENANT entry matching {metric_sub!r}"
        )
    return get


def _bench_collective(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_COLLECTIVE.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(f"no BENCH_COLLECTIVE entry matching {metric_sub!r}")
    return get


def _bench_paged_kv(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_PAGED_KV.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(f"no BENCH_PAGED_KV entry matching {metric_sub!r}")
    return get


def _bench_serve_macro(metric_sub: str, field: str):
    def get():
        for e in _load("BENCH_SERVE_MACRO.json"):
            if metric_sub in e.get("metric", ""):
                return e[field]
        raise KeyError(f"no BENCH_SERVE_MACRO entry matching {metric_sub!r}")
    return get


def _bench_r(field: str, sub: str = None):
    def get():
        d = _load("BENCH_TPU_LIVE.json")
        if sub:
            d = d[sub]
        return d[field]
    return get


def _rtlint_rule_count():
    def get():
        if REPO not in sys.path:  # direct `python tools/check_claims.py`
            sys.path.insert(0, REPO)
        from tools.rtlint.rules import ALL_RULES

        return len(ALL_RULES)
    return get


def _rtlint_baseline_size():
    def get():
        data = _load(os.path.join("tools", "rtlint", "baseline.json"))
        return sum(data["findings"].values())
    return get


_RTLINT_RUN = {}


def _rtlint_run():
    """One live engine run over the default targets, shared by every
    suppression-count claim (MIGRATION.md's triage table must track
    the code, not a hand-maintained tally)."""
    if not _RTLINT_RUN:
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from tools.rtlint import DEFAULT_TARGETS, analyze_paths

        targets = [os.path.join(REPO, t) for t in DEFAULT_TARGETS
                   if "*" not in t]
        targets += glob.glob(os.path.join(REPO, "bench_*.py"))
        _RTLINT_RUN["result"] = analyze_paths(targets, root=REPO)
    return _RTLINT_RUN["result"]


def _rtlint_suppressed(rule: str = None):
    def get():
        res = _rtlint_run()
        if rule is None:
            return sum(res.suppressed.values())
        return res.suppressed.get(rule, 0)
    return get


def _rtlint_found(rule: str):
    def get():
        return sum(1 for f in _rtlint_run().findings if f.rule == rule)
    return get


class Claim:
    def __init__(self, doc: str, pattern: str, getter: Callable,
                 rel_tol: float = 0.15, scale: float = 1.0,
                 note: str = ""):
        self.doc = doc
        self.pattern = pattern
        self.getter = getter
        self.rel_tol = rel_tol
        self.scale = scale  # doc units -> artifact units (k -> 1000)
        self.note = note

    def check(self) -> List[str]:
        """Returns a list of problem strings (empty = ok)."""
        path = os.path.join(REPO, self.doc)
        text = open(path).read()
        matches = re.findall(self.pattern, text)
        if not matches:
            return [f"{self.doc}: pattern {self.pattern!r} not found "
                    f"(doc rewritten? update tools/check_claims.py)"]
        try:
            actual = float(self.getter())
        except (KeyError, FileNotFoundError) as e:
            return [f"{self.doc}: artifact lookup failed for "
                    f"{self.pattern!r}: {e}"]
        problems = []
        for m in matches:
            quoted = float(m) * self.scale
            if actual == 0:
                ok = quoted == 0
            else:
                ok = abs(quoted - actual) / abs(actual) <= self.rel_tol
            if not ok:
                problems.append(
                    f"{self.doc}: quoted {quoted:g} vs artifact "
                    f"{actual:g} (pattern {self.pattern!r}"
                    f"{'; ' + self.note if self.note else ''})"
                )
        return problems


CLAIMS = [
    # README headline flagship numbers <- live TPU artifact.
    Claim("README.md", r"MFU (0\.\d+)", _bench_r("mfu"), rel_tol=0.08),
    Claim("README.md", r"(\d+\.\d+)k tokens/s/chip", _bench_r("value"),
          scale=1000.0, rel_tol=0.08),
    # README pipelined throughput <- BENCH_CORE.
    Claim("README.md", r"~(\d+\.?\d*)k pipelined tasks/s",
          _bench_core("tasks async", "ops_per_s"), scale=1000.0,
          rel_tol=0.2),
    Claim("README.md", r"~(\d+\.?\d*)k pipelined actor calls",
          _bench_core("actor calls async", "ops_per_s"), scale=1000.0,
          rel_tol=0.2),
    Claim("README.md", r"actor register\+ready\+call ~(\d+)/s",
          _bench_core("register+ready", "ops_per_s"), rel_tol=0.35),
    # COMPONENTS direct-transport tasks/s <- BENCH_CORE.
    Claim("COMPONENTS.md", r"~(\d+\.?\d*)k pipelined tasks/s",
          _bench_core("tasks async", "ops_per_s"), scale=1000.0,
          rel_tol=0.2),
    # COMPONENTS broadcast wall clock <- BENCH_SCALE steady-state.
    Claim("COMPONENTS.md", r"256MB->4 nodes (\d+\.?\d*)s",
          _bench_scale_broadcast(4, "wall_s"), rel_tol=0.5,
          note="steady-state broadcast wall"),
    Claim("README.md", r"\*\*(0\.\d+)s to 4\s*\n?\s*nodes",
          _bench_scale_broadcast(4, "wall_s"), rel_tol=0.5),
    Claim("README.md", r"(\d+)µs/task on one core",
          _bench_scale_tasks(1_000_000, "us_per_task"), rel_tol=0.3),
    # COMPONENTS flagship MFU <- live TPU artifact.
    Claim("COMPONENTS.md", r"MFU (0\.\d+)", _bench_r("mfu"), rel_tol=0.08),
    # Serving-engine hot-loop numbers <- BENCH_INFER stepwise probe.
    # Quoted in MIGRATION.md and the bench_infer.py probe docstring;
    # tight tolerance — docs and artifact are committed together.
    Claim("MIGRATION.md", r"engine step (\d+\.\d+) ms",
          _bench_infer("engine step breakdown", "engine_step_ms"),
          rel_tol=0.02),
    Claim("MIGRATION.md", r"raw decode floor (\d+\.\d+) ms",
          _bench_infer("engine step breakdown", "raw_decode_step_ms"),
          rel_tol=0.02),
    Claim("MIGRATION.md", r"throughput ratio (\d+\.\d+)",
          _bench_infer("engine vs raw decode throughput",
                       "engine_vs_raw_throughput_ratio"),
          rel_tol=0.02),
    Claim("MIGRATION.md", r"pins (\d+) compiles",
          _bench_infer("engine step breakdown", "compiles_in_window")),
    Claim("MIGRATION.md", r"and (\d+) param uploads",
          _bench_infer("engine step breakdown",
                       "param_uploads_in_window")),
    Claim("MIGRATION.md", r"implied (\d+\.\d+) ms/step",
          _bench_infer_r5_implied_step_ms(), rel_tol=0.02,
          note="r5 engine step implied by 4 slots / continuous tok/s"),
    Claim("MIGRATION.md", r"a (\d+\.\d+) ms raw batch-8 decode",
          _bench_infer("llama2(0.8B) decode", "ms_per_decode_step",
                       batch=8),
          rel_tol=0.02),
    Claim("bench_infer.py", r"step (\d+\.\d+) ms vs raw floor",
          _bench_infer("engine step breakdown", "engine_step_ms"),
          rel_tol=0.02),
    Claim("bench_infer.py", r"vs raw floor (\d+\.\d+) ms",
          _bench_infer("engine step breakdown", "raw_decode_step_ms"),
          rel_tol=0.02),
    Claim("bench_infer.py", r"overhead (-?\d+\.\d+) ms",
          _bench_infer("engine step breakdown", "engine_overhead_ms"),
          rel_tol=0.05),
    Claim("bench_infer.py", r"ratio of (\d+\.\d+)",
          _bench_infer("engine vs raw decode throughput",
                       "engine_vs_raw_throughput_ratio"),
          rel_tol=0.02),
    Claim("bench_infer.py", r"implied (\d+\.\d+) ms engine step",
          _bench_infer_r5_implied_step_ms(), rel_tol=0.02),
    Claim("bench_infer.py", r"artifact's (\d+\.\d+) ms raw batch-8",
          _bench_infer("llama2(0.8B) decode", "ms_per_decode_step",
                       batch=8),
          rel_tol=0.02),
    # Input-pipeline feed numbers <- BENCH_DATA.json (bench_data.py).
    # Tight tolerance: docs and artifact are committed together.
    Claim("MIGRATION.md", r"serial feed (\d+\.\d+) batches/s",
          _bench_data("feed throughput", "serial_batches_per_s"),
          rel_tol=0.02),
    Claim("MIGRATION.md", r"pipelined (\d+\.\d+) batches/s",
          _bench_data("feed throughput", "pipelined_batches_per_s"),
          rel_tol=0.02),
    Claim("MIGRATION.md", r"feed speedup (\d+\.\d+)x",
          _bench_data("feed throughput", "speedup"), rel_tol=0.02),
    Claim("MIGRATION.md", r"overlap ratio (0\.\d+)",
          _bench_data("feed throughput", "overlap_ratio"), rel_tol=0.02),
    Claim("MIGRATION.md", r"resolves in\s*\n?\s*(\d+\.\d+) probe rounds",
          _bench_data("multi-ref get", "parallel_probe_rounds"),
          rel_tol=0.1),
    Claim("MIGRATION.md", r"vs (\d+\.\d+) serially",
          _bench_data("multi-ref get", "serial_probe_rounds"),
          rel_tol=0.1),
    Claim("MIGRATION.md", r"multi-ref speedup (\d+\.\d+)x",
          _bench_data("multi-ref get", "speedup"), rel_tol=0.02),
    # Fault-tolerance latencies <- BENCH_FT.json (bench_ft.py). Loose
    # tolerances: these are wall-clock timings of control-plane paths on
    # a shared CI box (detection additionally quantizes to the 50ms poll
    # cadence).
    Claim("MIGRATION.md", r"kill-to-detection ~(\d+\.?\d*) ms",
          _bench_ft("kill-to-detection", "detect_ms"), rel_tol=0.5),
    Claim("MIGRATION.md", r"gang rebuild ~(\d+\.?\d*) ms",
          _bench_ft("gang rebuild", "rebuild_s"), scale=0.001,
          rel_tol=1.0, note="pipelined actor respawn; noisy at ~20ms"),
    Claim("MIGRATION.md", r"deadline trips in (\d+\.\d+) s",
          _bench_ft("collective timeout trip", "trip_s"), rel_tol=0.1),
    # Flight-recorder overhead <- BENCH_OBS.json (bench_obs.py). Loose
    # tolerances: sub-% overhead measured on a shared CI box; the CLAIM
    # is "well under 2%", the exact digits wobble run to run.
    Claim("MIGRATION.md", r"emission\) adds (\d+\.\d+)%",
          _bench_obs("step recorder overhead", "overhead_pct"),
          rel_tol=2.0, note="paired-median overhead, noisy at sub-%"),
    Claim("MIGRATION.md", r"recorder adds (\d+\.\d+) µs/step",
          _bench_obs("step recorder overhead", "recorder_cost_us_per_step"),
          rel_tol=1.0),
    Claim("MIGRATION.md", r"empty-step floor of (\d+\.\d+) µs",
          _bench_obs("recorder cost, empty steps", "cost_us_per_step"),
          rel_tol=1.0),
    Claim("MIGRATION.md", r"(\d+\.\d+) ms at 256 live arrays",
          _bench_obs("memory accountant sample", "sample_ms"),
          rel_tol=1.0),
    # Cluster black box <- BENCH_OBS.json journal probes. The step-wall
    # delta hovers around zero on a shared box, so the doc quotes the
    # gate, not the digit; these pin the stable numbers.
    Claim("MIGRATION.md", r"one `emit\(\)` costs (\d+\.\d+) µs",
          _bench_obs("journal emit cost", "emit_us"),
          rel_tol=1.0, note="µs micro-bench, noisy on a shared box"),
    Claim("MIGRATION.md", r"\((\d+) steps per arm, interleaved",
          _bench_obs("journal overhead", "steps_per_arm"), rel_tol=0.0),
    Claim("MIGRATION.md", r"(\d+)-emit probe",
          _bench_obs("journal emit cost", "emits"), rel_tol=0.0),
    Claim("MIGRATION.md", r"`RT_JOURNAL_RING` \(default (\d+)\)",
          _bench_obs("journal emit cost", "ring"), rel_tol=0.0),
    # Request observatory <- BENCH_SERVE_OBS.json (bench_serve_obs.py).
    # The decode-overhead median hovers around zero on a shared box, so
    # the doc quotes the gate, not the digit; these pin the stable
    # numbers.
    Claim("MIGRATION.md", r"(\d+\.\d+) µs of\s*\n?\s*bookkeeping per request",
          _bench_serve_obs("observatory cost, synthetic",
                           "cost_us_per_request"),
          rel_tol=1.0, note="µs micro-bench, noisy on a shared box"),
    Claim("MIGRATION.md", r"median of (\d+)\s*\n?\s*paired",
          _bench_serve_obs("steady-state decode overhead", "pairs"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"explains (\d+\.\d+) of\s*\n?\s*each request",
          _bench_serve_obs("phase-sum fraction", "mean_fraction"),
          rel_tol=0.02),
    Claim("MIGRATION.md", r"a (\d+\.\d+) s\s*\n?\s*chaos-injected prefill",
          _bench_serve_obs("HOL watchdog", "injected_prefill_s"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"as (\d+\.\d+) blocked slot-seconds",
          _bench_serve_obs("HOL watchdog", "blocked_slot_seconds"),
          rel_tol=0.25, note="injected 0.2s + one real prefill pass"),
    # Serve survival plane <- BENCH_SERVE_FT.json (bench_serve_ft.py).
    # Wall-clock probes on a shared box get loose tolerances; the zero
    # lost-request pins are exact — any loss must fail the doc check.
    Claim("MIGRATION.md", r"shed decision costs (\d+\.\d+) µs",
          _bench_serve_ft("shed decision latency", "shed_p50_us"),
          rel_tol=1.0, note="µs micro-bench, noisy on a shared box"),
    Claim("MIGRATION.md", r"sheds every request\s*\n?\s*with a "
                          r"(\d+\.\d+) ms p99",
          _bench_serve_ft("shed decision latency", "shed_p99_ms"),
          rel_tol=1.0, note="p99 of a µs-scale decision"),
    Claim("MIGRATION.md", r"p99 TTFT at (\d+\.\d+)× the",
          _bench_serve_ft("replica chaos", "chaos_over_baseline_p99"),
          rel_tol=1.0, note="ratio hovers just above 1 on a quiet box"),
    Claim("MIGRATION.md", r"drains in (\d+\.\d+) s median",
          _bench_serve_ft("graceful drain", "drain_p50_s"), rel_tol=0.5),
    Claim("MIGRATION.md", r"answers again in\s*\n?\s*(\d+\.\d+) s",
          _bench_serve_ft("controller kill+restart",
                          "controller_recovery_s"),
          rel_tol=2.0, note="named-actor restart + checkpoint restore"),
    Claim("MIGRATION.md", r"traffic loses (\d+) requests",
          _bench_serve_ft("controller kill+restart",
                          "requests_failed"), rel_tol=0.0),
    Claim("MIGRATION.md", r"with (\d+) lost non-shed requests",
          _bench_serve_ft("survival plane summary",
                          "lost_requests_total"), rel_tol=0.0),
    # Multi-tenancy / preemption <- BENCH_MULTITENANT.json
    # (bench_multitenant.py). Wall-clock probes get loose tolerances;
    # the zero-lost pin is exact, and the hard-kill latency is grace-
    # dominated so it stays fairly tight.
    Claim("MIGRATION.md", r"spike is\s*\n?\s*answering in (\d+\.\d+) s",
          _bench_multitenant("graceful reclamation",
                             "spike_deploy_to_first_response_s"),
          rel_tol=1.0, note="drain+checkpoint+respawn wall clock"),
    Claim("MIGRATION.md", r"places (\d+\.\d+) s after the\s*\n?\s*claim",
          _bench_multitenant("hard-kill deadline",
                             "spike_wait_to_placed_s"),
          rel_tol=0.4, note="grace deadline (3 s) + kill/force-remove"),
    Claim("MIGRATION.md", r"saw\s*\n?\s*(\d+) lost non-shed",
          _bench_multitenant("three-tenant SLO accounting",
                             "lost_non_shed"), rel_tol=0.0),
    # Elastic training <- the elastic-vs-evict probe of the same
    # artifact. Steps lost and the step target are exact pins; the
    # goodput ratio is wall-clock so it gets a loose tolerance.
    Claim("MIGRATION.md", r"holds them for (\d+) s",
          _bench_multitenant("elastic resize", "chips_held_s"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"finished all (\d+)\s*\n?\s*steps",
          _bench_multitenant("elastic resize", "steps"), rel_tol=0.0),
    Claim("MIGRATION.md", r"losing (\d+) steps",
          lambda: _bench_multitenant("elastic resize", "elastic")()
          ["steps_lost"], rel_tol=0.0),
    Claim("MIGRATION.md", r"delivered (\d+\.\d+)× the goodput",
          _bench_multitenant("elastic resize", "goodput_ratio"),
          rel_tol=0.5, note="wall-clock dependent; gate is > 1.0"),
    # Static-analysis section <- rtlint itself. Exact pins (rel_tol=0):
    # adding a rule or regenerating the baseline must update the doc.
    Claim("MIGRATION.md", r"lint pass\s*\n?\s*with (\d+) rules",
          _rtlint_rule_count(), rel_tol=0.0),
    Claim("MIGRATION.md", r"holds (\d+) known findings",
          _rtlint_baseline_size(), rel_tol=0.0),
    # v2 dogfood triage table <- a live engine run over the default
    # targets (exact pins: drift means a suppression was added or
    # removed without updating the doc).
    Claim("MIGRATION.md", r"RT008: (\d+) suppressed",
          _rtlint_suppressed("RT008"), rel_tol=0.0),
    Claim("MIGRATION.md", r"RT009: (\d+) suppressed",
          _rtlint_suppressed("RT009"), rel_tol=0.0),
    Claim("MIGRATION.md", r"RT010: (\d+) suppressed",
          _rtlint_suppressed("RT010"), rel_tol=0.0),
    Claim("MIGRATION.md", r"RT011: (\d+) suppressed",
          _rtlint_suppressed("RT011"), rel_tol=0.0),
    Claim("MIGRATION.md", r"RT012: (\d+) findings",
          _rtlint_found("RT012"), rel_tol=0.0),
    Claim("MIGRATION.md", r"RT013: (\d+) suppressed",
          _rtlint_suppressed("RT013"), rel_tol=0.0),
    Claim("MIGRATION.md", r"suppresses (\d+) findings across",
          _rtlint_suppressed(), rel_tol=0.0),
    Claim("MIGRATION.md", r"carries (\d+) baselined findings",
          _rtlint_baseline_size(), rel_tol=0.0),
    # Control-plane profiler <- BENCH_SCALE.json lifecycle probes.
    # Loose tolerances on the absolute µs (wall timings on a shared
    # 1-core box); tight on the coverage fraction, which is the claim.
    Claim("MIGRATION.md", r"explain (0\.\d+)\s*\n?\s*of the mean",
          _bench_scale_lifecycle(1000, "phase_sum_fraction_of_e2e"),
          rel_tol=0.05),
    Claim("MIGRATION.md", r"transport at ~(\d+) µs",
          _bench_scale_lifecycle(1000, None, phase="transport"),
          rel_tol=0.5),
    Claim("MIGRATION.md", r"of a (\d+) µs\s*\n?\s*mean submit",
          _bench_scale_lifecycle(1000, "us_per_task"), rel_tol=0.5),
    Claim("MIGRATION.md", r"costs (\d+\.?\d*) GCS round-trips",
          _bench_scale_probe("gcs rpcs per actor create",
                             "gcs_rpcs_per_actor_create"),
          rel_tol=0.3),
    Claim("MIGRATION.md", r"guard ops cost (\d+\.\d+) µs",
          _bench_scale_probe("lifecycle off-path overhead",
                             "fastpath_ops_us_per_task"),
          rel_tol=1.5, note="sub-µs micro-bench, noisy on a shared box"),
    # Topology-native collectives <- BENCH_COLLECTIVE.json
    # (bench_collective.py). Byte counts and the cost-model crossover
    # are deterministic (tight pins); the latency speedup is wall clock
    # on a shared box (loose).
    Claim("MIGRATION.md", r"crossover at (\d+) KiB",
          _bench_collective("algorithm selection", "crossover_KiB"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"moves (0\.\d+) of the flat ring's DCN bytes",
          _bench_collective("sharded-hier DCN bytes", "ratio"),
          rel_tol=0.05),
    Claim("MIGRATION.md", r"cuts DCN wire bytes (\d+\.\d+)×",
          _bench_collective("int8 quantized DCN allreduce",
                            "wire_reduction"), rel_tol=0.02),
    Claim("MIGRATION.md", r"max relative error (0\.\d+)",
          _bench_collective("int8 quantized DCN allreduce",
                            "max_rel_error"), rel_tol=0.1),
    Claim("MIGRATION.md", r"to (0\.\d+) over 20 error-feedback steps",
          _bench_collective("int8 quantized DCN allreduce",
                            "ef_mean_error_20_steps"), rel_tol=0.25),
    Claim("MIGRATION.md", r"recursive doubling beats it (\d+\.\d+)×",
          _bench_collective("rd vs ring latency", "speedup"),
          rel_tol=0.5, note="wall-clock ratio under injected latency"),
    # Paged KV engine <- BENCH_PAGED_KV.json (bench_paged_kv.py).
    # Peak concurrency, skipped-token and page counts are deterministic
    # (tight pins); TTFT and the scale-up time are wall clock (loose).
    Claim("MIGRATION.md", r"peaks at (\d+) concurrent requests paged",
          _bench_paged_kv("mixed-length peak", "paged_peak_concurrent"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"vs (\d+) slotted \(gate",
          _bench_paged_kv("mixed-length peak", "slotted_peak_concurrent"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"first token (\d+\.\d+)× faster",
          _bench_paged_kv("shared-prefix TTFT", "speedup"),
          rel_tol=0.5, note="wall-clock ratio on a shared box"),
    Claim("MIGRATION.md", r"\((\d+\.\d+) ms warm",
          _bench_paged_kv("shared-prefix TTFT", "warm_ttft_ms"),
          rel_tol=1.0, note="ms-scale wall clock on a shared box"),
    Claim("MIGRATION.md", r"(\d+\.\d+) ms cold",
          _bench_paged_kv("shared-prefix TTFT", "cold_ttft_ms"),
          rel_tol=1.0, note="ms-scale wall clock on a shared box"),
    Claim("MIGRATION.md", r"counter reading exactly (\d+) tokens",
          _bench_paged_kv("shared-prefix TTFT", "prefill_tokens_skipped"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"is (0\.\d+) blocked slot-seconds",
          _bench_paged_kv("head-of-line", "hol_blocked_s"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"app to (\d+) replicas in",
          _bench_paged_kv("autoscaler ramp", "peak_replicas"),
          rel_tol=0.4, note="peak depends on ramp timing; gate is >= 2"),
    Claim("MIGRATION.md", r"replicas in (\d+\.\d+) s under",
          _bench_paged_kv("autoscaler ramp", "scale_up_s"),
          rel_tol=1.5, note="wall clock against a 0.5 s signals tick"),
    Claim("MIGRATION.md", r"(\d+) lost non-shed requests; and",
          _bench_paged_kv("autoscaler ramp", "lost_non_shed"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"(\d+) resident cache pages",
          _bench_paged_kv("page-leak", "cache_pages_flushed"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"exactly (\d+) pages in use",
          _bench_paged_kv("page-leak", "pages_in_use_after"),
          rel_tol=0.0),
    # -- serve macro (cluster witness) claims -> BENCH_SERVE_MACRO.json
    Claim("MIGRATION.md", r"sustains (\d+\.\d+) QPS achieved",
          _bench_serve_macro("sustained macro", "achieved_qps"),
          rel_tol=0.25),
    Claim("MIGRATION.md", r"against (\d+\.\d+)\s*\n?\s*offered",
          _bench_serve_macro("sustained macro", "offered_qps"),
          rel_tol=0.25),
    Claim("MIGRATION.md", r"unattributed gap p99 (\d+\.\d+) ms",
          _bench_serve_macro("sustained macro", "gap_p99_ms"),
          rel_tol=3.0, note="ms-scale dispatch jitter run to run"),
    Claim("MIGRATION.md", r"gap fraction p99 (0\.\d+) against",
          _bench_serve_macro("sustained macro", "gap_fraction_p99"),
          rel_tol=3.0, note="ms-scale dispatch jitter run to run"),
    Claim("MIGRATION.md", r"(\d+) lost non-shed\s*\n?\s*requests",
          _bench_serve_macro("chaos macro", "lost_non_shed"),
          rel_tol=0.0),
    Claim("MIGRATION.md", r"out of (\d+), client TTFB",
          _bench_serve_macro("chaos macro", "issued"), rel_tol=0.0),
    Claim("MIGRATION.md", r"client TTFB p99 held at (\d+) ms",
          _bench_serve_macro("chaos macro", "client_ttfb_p99_ms"),
          rel_tol=1.0),
    Claim("MIGRATION.md", r"after the kill was (\d+\.\d+) s",
          _bench_serve_macro("chaos macro", "recovery_s"),
          rel_tol=3.0, note="respawn timing varies run to run"),
    Claim("MIGRATION.md", r"tracked the ramp to (\d+) replicas",
          _bench_serve_macro("chaos macro", "autoscaler_max_target"),
          rel_tol=0.34, note="2-4 replica band is healthy"),
    Claim("MIGRATION.md", r"regenerates the (\d+)-request",
          _bench_serve_macro("record/replay", "requests"),
          rel_tol=0.0, note="pure function of the committed seed"),
]


def check_all() -> List[str]:
    problems: List[str] = []
    for claim in CLAIMS:
        problems.extend(claim.check())
    return problems


def main() -> int:
    problems = check_all()
    if problems:
        for p in problems:
            print(f"STALE: {p}")
        return 1
    print(f"all {len(CLAIMS)} doc claims match their artifacts")
    return 0


if __name__ == "__main__":
    sys.exit(main())
