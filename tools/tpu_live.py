"""Opportunistic TPU bench capture.

The axon TPU tunnel can be dead for hours; ``jax.devices()`` then hangs
forever.  This daemon probes the tunnel cheaply (subprocess + timeout) on
a loop and, the moment the tunnel answers, runs the flagship bench
(``bench.py``) and commits a timestamped ``BENCH_TPU_LIVE.json`` so a
driver-verified TPU artifact exists even if the end-of-round bench window
hits a dead tunnel.  (VERDICT r3 item 1b.)

Run:  python tools/tpu_live.py [--once]
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import _probe_tunnel  # single source of truth for the probe

OUT = os.path.join(REPO, "BENCH_TPU_LIVE.json")
PROBE_INTERVAL = float(os.environ.get("RT_TPU_PROBE_INTERVAL", 180))
SESSION_DEADLINE = float(os.environ.get("RT_TPU_SESSION_DEADLINE", 10.5 * 3600))
BENCH_TIMEOUT = float(os.environ.get("RT_TPU_BENCH_TIMEOUT", 1800))


def log(msg: str) -> None:
    print(f"[tpu_live] {time.strftime('%H:%M:%S')} {msg}", file=sys.stderr, flush=True)


def run_bench() -> dict | None:
    """Run the flagship bench; return the parsed JSON line if it is a fresh
    TPU measurement (bench.py's own cached-artifact fallback is rejected)."""
    env = dict(os.environ)
    # The probe just proved the tunnel; skip bench.py's own probe phase and
    # go straight to full attempts.
    env["RT_BENCH_PROBE_DEADLINE"] = "90"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py")],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        cwd=REPO,
        start_new_session=True,  # own process group: timeout kill sweeps the
    )                            # jax worker grandchildren too
    try:
        stdout, _ = proc.communicate(timeout=BENCH_TIMEOUT)
    except subprocess.TimeoutExpired:
        log("bench timed out; killing process group")
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        return None
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if parsed.get("cached"):
                log("bench emitted its cached artifact, not a fresh run")
                return None
            if "tpu" in str(parsed.get("device", "")).lower():
                return parsed
            log(f"bench fell back off-TPU: device={parsed.get('device')}")
            return None
    log(f"bench produced no JSON (rc={proc.returncode})")
    return None


def commit(result: dict) -> None:
    result["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(OUT, "w") as f:
        json.dump(result, f, indent=1)
    subprocess.run(["git", "add", "--", "BENCH_TPU_LIVE.json"], cwd=REPO)
    subprocess.run(
        ["git", "commit", "-m", "Capture live TPU flagship bench artifact",
         "--only", "--", "BENCH_TPU_LIVE.json"],
        cwd=REPO,
    )
    log(f"captured: {result.get('value')} {result.get('unit')} "
        f"mfu={result.get('mfu')} vs_baseline={result.get('vs_baseline')}")


def main() -> int:
    once = "--once" in sys.argv
    t0 = time.monotonic()
    n = 0
    while time.monotonic() - t0 < SESSION_DEADLINE:
        n += 1
        if _probe_tunnel():
            log(f"probe {n}: tunnel ALIVE — running flagship bench")
            result = run_bench()
            if result is not None:
                commit(result)
                return 0
        else:
            log(f"probe {n}: tunnel dead")
        if once:
            return 1
        time.sleep(PROBE_INTERVAL)
    return 1


if __name__ == "__main__":
    sys.exit(main())
