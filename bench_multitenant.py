"""Three tenants on one cluster surviving each other's demand spikes.
Writes BENCH_MULTITENANT.json.

The multi-tenancy story is only real if one run shows all three tenants'
SLOs while chips move between them, so this bench builds an in-process
cluster (1 CPU head + 2 simulated TPU hosts, 4 chips each) and runs a
training gang, a serve app, and CPU rollout actors feeding an RL learner
SIMULTANEOUSLY — then takes the chips away and gives them back:

  1. graceful reclamation: the training gang (priority 0) holds all 8
     chips; a latency-critical serve spike (priority 10, TPU:4) deploys.
     The GCS reclamation pass drains the gang's nodes, the trainer
     checkpoints and stops (PR 2 proactive migration), the spike places
     on the fenced chips. When the spike is deleted, the gang's
     re-queued placement group places at its original priority and
     training resumes FROM THE NEWEST CHECKPOINT and completes. Gates:
     spike served within 30 s of deploy, training completed every step,
     resumed step > 0 (not from scratch), victim record outcome
     "graceful".
  2. chips returned: after the spike subsides and training finishes,
     both TPU hosts report all chips available and nothing is left
     draining or fenced. Gate: 8/8 chips free, zero open preemptions.
  3. three-tenant SLO accounting: closed-loop chat traffic runs the
     whole time under tenant labels "train"/"serve"/"rl"; the metrics
     snapshot must carry per-tenant request series and SLO burn for all
     three in ONE run. Gates: all three tenants present, zero lost
     non-shed requests across both phases.
  4. hard-kill deadline under mid-drain chaos: a "deaf" gang (ignores
     drain) holds all chips; a second spike triggers reclamation;
     chaos.kill_victim_mid_drain() kills a victim actor mid-drain. The
     grace deadline must still converge: remaining actors killed, group
     force-released, spike placed, no wedged placement groups. Gates:
     release within grace + slack, outcome "hard_kill", spike placed,
     zero PENDING groups at the end.
  5. elastic resize vs evict-and-restart: the same 8->4->8 partial
     reclamation (chaos claims 4 of the gang's 8 chips, holds them,
     lets go) hits two identical training runs. The elastic gang
     shrinks in place (survivor keeps stepping on 4 chips, state
     re-sharded through the object store) and grows back on the fence
     lift; the fixed gang checkpoints, evicts, and sits idle until the
     chips return. Gates: elastic run's step history is gapless across
     both resizes (zero lost steps), its victim record closes with the
     elastic outcome "resized", final loss matches the evict-restart
     run exactly, and goodput (steps per wall-second through the
     incident) beats the evict-and-restart baseline.

Run: python bench_multitenant.py [--quick]  (--quick: shorter phases,
no artifact). Exits non-zero when a gate fails.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RT_TPU_CHIPS", "0")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

TRAIN_STEPS = 60          # full-run training step target
STEP_S = 0.15             # per-step work (gang must outlive the spike)
SPIKE_HOLD_S = 2.5        # how long the serve spike keeps the chips
HARD_GRACE_S = 3.0        # phase-B grace window (deaf gang hard kill)


def _train_loop(config):
    """Checkpoint-every-step cooperative loop: on drain it saves and
    returns at the next should_stop() check (zero lost steps)."""
    import time as _t

    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        start = ckpt.to_dict()["step"] + 1
    for step in range(start, config["steps"]):
        _t.sleep(config["step_s"])
        train.report({"step": step, "start": start},
                     checkpoint=Checkpoint.from_dict({"step": step}))
        if train.should_stop():
            return  # checkpointed above; migrate with zero lost work
    return


def _elastic_vs_restart_loop(config):
    """One loop, two failure modes. Elastic gangs resize through
    train.sync_resize (live state handoff); fixed gangs checkpoint every
    step and honor should_stop (the PR 2 migrate path). Reporting and
    checkpoint cadence are identical so the goodput comparison is
    fair."""
    import time as _t

    import numpy as np

    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    state = {"w": np.zeros(4, dtype=np.float64), "steps_done": 0}
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        d = ckpt.to_dict()
        state = {"w": np.asarray(d["w"]), "steps_done": d["steps_done"]}
    shards = train.shard_state(
        {"m": np.arange(32, dtype=np.float64)}, name="opt")
    while state["steps_done"] < config["steps"]:
        ev = train.sync_resize(state, shards)
        if ev.exiting:
            return  # departing rank: slice persisted, exit clean
        state, shards = ev.state, ev.shards
        _t.sleep(config["step_s"])
        state["w"] += 1.0
        state["steps_done"] += 1
        ck = Checkpoint.from_dict(
            {"w": state["w"].tolist(), "steps_done": state["steps_done"]})
        if train.get_world_rank() == 0:
            train.report(
                {"step": state["steps_done"], "world": ev.world_size,
                 "loss": abs(float(state["w"].mean())
                             - state["steps_done"])},
                checkpoint=ck)
        else:
            train.report({"step": state["steps_done"]})
        if train.should_stop():
            return  # fixed-size path: checkpointed above, migrate


def _wait_for(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def main():
    quick = "--quick" in sys.argv
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu._private import chaos
    from ray_tpu._private.config import get_config
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.serve.deployment import SloConfig
    from ray_tpu.train.backend import JaxConfig
    from ray_tpu.train.config import (
        FailureConfig,
        ResizePolicy,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.train.trainer import DataParallelTrainer
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    steps = 35 if quick else TRAIN_STEPS
    cfg = get_config()
    cfg.preempt_grace_s = 20.0  # phase A: graceful path must win

    results = []
    cluster = Cluster()
    cluster.add_node(num_cpus=8)  # head: CPU tenants only
    w1 = cluster.add_node(num_cpus=4, num_tpus=4)
    w2 = cluster.add_node(num_cpus=4, num_tpus=4)
    client = cluster.connect()
    gcs = cluster.gcs
    tpu_nodes = (w1.node_id.binary(), w2.node_id.binary())

    trial_dir = f"/tmp/bench_multitenant_{os.getpid()}"

    # -- tenant 2: serve "chat" app, traffic under 3 tenant labels ------
    @serve.deployment(num_replicas=2,
                      ray_actor_options={"num_cpus": 0.5},
                      slo=SloConfig(e2e_ms=500.0, objective=0.99))
    def chat(x):
        time.sleep(0.005)
        return x + 1

    chat_h = serve.run(chat.bind())
    assert chat_h.remote(0).result(timeout=60) == 1  # warm routes

    chat_ok = {"train": 0, "serve": 0, "rl": 0}
    chat_lost, chat_shed = [], [0]
    stop_traffic = threading.Event()

    def chat_client(tenant):
        from ray_tpu.exceptions import ServeOverloadedError

        h = chat_h.options(tenant=tenant)
        i = 0
        while not stop_traffic.is_set():
            try:
                if h.remote(i).result(timeout=60) == i + 1:
                    chat_ok[tenant] += 1
                else:
                    chat_lost.append("wrong result")
            except ServeOverloadedError:
                chat_shed[0] += 1
            except Exception as e:  # noqa: BLE001 — tally, gate below
                chat_lost.append(f"{type(e).__name__}: {e}")
            i += 1
            time.sleep(0.02)

    traffic = [threading.Thread(target=chat_client, args=(t,), daemon=True)
               for t in ("train", "serve", "rl")]
    for t in traffic:
        t.start()

    # -- tenant 3: RL rollout actors feeding a learner ------------------
    @rt.remote(num_cpus=1)
    class Rollout:
        def step(self, i):
            return [i] * 8

    rollouts = [Rollout.remote() for _ in range(2)]
    rl_steps = [0]
    stop_rl = threading.Event()

    def learner():
        i = 0
        while not stop_rl.is_set():
            try:
                batches = rt.get(
                    [r.step.remote(i) for r in rollouts], timeout=60
                )
                assert all(b == [i] * 8 for b in batches)
                rl_steps[0] += 1
            except Exception:  # noqa: BLE001 — rl gate counts progress
                pass
            i += 1
            time.sleep(0.02)

    rl_thread = threading.Thread(target=learner, daemon=True)
    rl_thread.start()

    # -- tenant 1: training gang on all 8 chips --------------------------
    trainer = DataParallelTrainer(
        _train_loop,
        train_loop_config={"steps": steps, "step_s": STEP_S},
        backend_config=JaxConfig(dp_sync="none"),
        scaling_config=ScalingConfig(
            num_workers=2,
            resources_per_worker={"CPU": 1, "TPU": 4},
            priority=0,
        ),
        run_config=RunConfig(
            name="gang", storage_path=trial_dir,
            failure_config=FailureConfig(max_failures=6, backoff_s=0.2,
                                         backoff_max_s=1.0),
        ),
    )
    fit_result = {}

    def fit():
        fit_result["result"] = trainer.fit()

    fit_thread = threading.Thread(target=fit, daemon=True)
    fit_thread.start()
    ckpt_index = os.path.join(trial_dir, "gang", "checkpoints",
                              "checkpoints.json")

    def _ckpts_registered():
        try:
            with open(ckpt_index) as f:
                return len(json.load(f))
        except (OSError, ValueError):
            return 0

    _wait_for(lambda: _ckpts_registered() >= 4, timeout=60,
              what="training checkpoints before the spike")

    # -- probe 1: serve spike reclaims chips gracefully ------------------
    @serve.deployment(ray_actor_options={"num_cpus": 0.5,
                                         "resources": {"TPU": 4},
                                         "priority": 10})
    def spike(x):
        return x * 2

    t0 = time.perf_counter()
    spike_h = serve.run(spike.bind())
    assert spike_h.remote(21).result(timeout=60) == 42  # placed + serving
    reclaim_s = time.perf_counter() - t0
    rl_at_spike = rl_steps[0]
    recs = [r for r in gcs.preemptions.values()
            if r["victim_tenant"] == "train"]
    time.sleep(SPIKE_HOLD_S if not quick else 1.0)
    serve.delete("spike")
    rl_during_spike = rl_steps[0] - rl_at_spike

    fit_thread.join(timeout=180)
    result = fit_result.get("result")
    history = result.metrics_history if result else []
    final_step = max((m.get("step", -1) for m in history), default=-1)
    resumed_from = max((m.get("start", 0) for m in history), default=0)
    victim_graceful = bool(recs) and recs[0]["outcome"] == "graceful"
    entry = {
        "metric": "graceful reclamation: serve spike evicts training gang",
        "spike_deploy_to_first_response_s": round(reclaim_s, 3),
        "train_steps_target": steps,
        "train_final_step": final_step,
        "train_resumed_from_step": resumed_from,
        "train_error": str(result.error) if result and result.error
        else None,
        "victim_outcome": recs[0]["outcome"] if recs else None,
        "gate": "spike served < 30 s; training completed all steps, "
                "resumed from checkpoint > 0; victim released gracefully",
        "pass": bool(
            reclaim_s < 30.0 and result is not None
            and result.error is None and final_step == steps - 1
            and resumed_from > 0 and victim_graceful
        ),
    }
    print(json.dumps(entry))
    results.append(entry)

    # -- probe 2: chips returned after the spike subsides ----------------
    def chips_free():
        return all(
            gcs.nodes[nid]["resources_available"].get("TPU", 0) == 4.0
            and not gcs.nodes[nid].get("draining")
            and gcs.nodes[nid].get("fenced_for") is None
            for nid in tpu_nodes
        )

    try:
        _wait_for(chips_free, timeout=30, what="chips returned")
        returned = True
    except AssertionError:
        returned = False
    open_recs = [r for r in gcs.preemptions.values()
                 if r["state"] != "released"]
    entry = {
        "metric": "chips returned to the pool after the spike",
        "tpu_free": sum(
            gcs.nodes[nid]["resources_available"].get("TPU", 0)
            for nid in tpu_nodes
        ),
        "tpu_total": 8,
        "open_preemptions": len(open_recs),
        "gate": "8/8 chips free, no node draining/fenced, zero open "
                "preemption records",
        "pass": returned and not open_recs,
    }
    print(json.dumps(entry))
    results.append(entry)

    # -- probe 5: elastic resize vs evict-and-restart --------------------
    el_steps = 60 if quick else 100
    el_step_s = 0.08
    el_hold_s = 2.0 if quick else 4.0

    def _ckpt_count(path):
        try:
            with open(path) as f:
                return len(json.load(f))
        except (OSError, ValueError):
            return 0

    def run_incident(name, elastic):
        """One training run through the same reclamation incident:
        warm up, chaos claims half the chips, holds them el_hold_s,
        lets go. Returns the run's scorecard."""
        trainer = DataParallelTrainer(
            _elastic_vs_restart_loop,
            train_loop_config={"steps": el_steps, "step_s": el_step_s},
            backend_config=JaxConfig(dp_sync="none"),
            scaling_config=ScalingConfig(
                num_workers=2,
                resources_per_worker={"CPU": 1, "TPU": 4},
                priority=0,
                elastic=ResizePolicy(min_world_size=1) if elastic
                else None,
            ),
            run_config=RunConfig(
                name=name, storage_path=trial_dir,
                failure_config=FailureConfig(max_failures=6, backoff_s=0.2,
                                             backoff_max_s=1.0),
            ),
        )
        holder = {}
        th = threading.Thread(
            target=lambda: holder.update(r=trainer.fit()), daemon=True)
        t0 = time.perf_counter()
        th.start()
        idx = os.path.join(trial_dir, name, "checkpoints",
                           "checkpoints.json")
        _wait_for(lambda: _ckpt_count(idx) >= 3, timeout=60,
                  what=f"{name}: warm-up steps before reclamation")
        victims = chaos.reclaim_chips(4, bundle_chips=4)
        time.sleep(el_hold_s)
        chaos.lift_fence()
        th.join(timeout=180)
        wall = time.perf_counter() - t0
        r = holder.get("r")
        history = r.metrics_history if r else []
        steps_seen = [m["step"] for m in history if "step" in m]
        rec = gcs.preemptions.get(victims[0]["victim_pg_id"]) if victims \
            else None
        return {
            "wall_s": round(wall, 3),
            "goodput_steps_per_s": round(el_steps / wall, 2),
            "final_step": max(steps_seen, default=-1),
            "steps_lost": el_steps - len(set(steps_seen)),
            "steps_replayed": len(steps_seen) - len(set(steps_seen)),
            "worlds": sorted({m["world"] for m in history
                              if "world" in m}),
            "final_loss": next((m["loss"] for m in reversed(history)
                                if "loss" in m), None),
            "victim_outcome": rec["outcome"] if rec else None,
            "error": str(r.error) if r and r.error else None,
        }

    chaos.enable()
    try:
        el = run_incident("elastic_gang", elastic=True)
        ev = run_incident("evict_gang", elastic=False)
    finally:
        chaos.disable()
        chaos.clear()
    goodput_ratio = (
        round(el["goodput_steps_per_s"] / ev["goodput_steps_per_s"], 2)
        if ev["goodput_steps_per_s"] else None
    )
    entry = {
        "metric": "elastic resize vs evict-and-restart under partial "
                  "reclamation",
        "steps": el_steps,
        "chips_held_s": el_hold_s,
        "elastic": el,
        "evict_restart": ev,
        "goodput_ratio": goodput_ratio,
        "gate": "elastic: zero lost steps, gapless history through "
                "2->1->2, victim outcome 'resized', final loss matches "
                "the evict-restart run; goodput_ratio > 1.0",
        "pass": bool(
            el["error"] is None and ev["error"] is None
            and el["steps_lost"] == 0 and el["steps_replayed"] == 0
            and el["final_step"] == el_steps
            and ev["final_step"] == el_steps
            and el["worlds"] == [1, 2]
            and el["victim_outcome"] == "resized"
            and el["final_loss"] is not None
            and ev["final_loss"] is not None
            and abs(el["final_loss"] - ev["final_loss"]) < 1e-9
            and goodput_ratio is not None and goodput_ratio > 1.0
        ),
    }
    print(json.dumps(entry))
    results.append(entry)

    # -- probe 4 (runs while chat traffic continues): hard-kill chaos ----
    cfg.preempt_grace_s = HARD_GRACE_S
    chaos.enable()
    deaf_killed_mid_drain = None
    try:
        deaf = placement_group([{"TPU": 4}, {"TPU": 4}], strategy="SPREAD",
                               name="deaf", priority=0)
        assert deaf.ready(timeout=15)

        @rt.remote(num_cpus=0, resources={"TPU": 1})
        class Deaf:
            def ping(self):
                return "ok"

        deaf_actor = Deaf.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=deaf, placement_group_bundle_index=0
            )
        ).remote()
        assert rt.get(deaf_actor.ping.remote(), timeout=60) == "ok"

        t0 = time.perf_counter()
        spike2 = placement_group([{"TPU": 4}], name="spike2", priority=10)
        _wait_for(
            lambda: (gcs.preemptions.get(deaf.id.binary()) or {})
            .get("state") == "draining",
            timeout=15, what="deaf gang draining",
        )
        deaf_killed_mid_drain = chaos.kill_victim_mid_drain()
        assert spike2.ready(timeout=HARD_GRACE_S + 15)
        released_s = time.perf_counter() - t0
        rec = gcs.preemptions[deaf.id.binary()]
        pending = [p for p in gcs.placement_groups.values()
                   if p["state"] == "PENDING"]
        entry = {
            "metric": "hard-kill deadline honored under mid-drain chaos",
            "grace_s": HARD_GRACE_S,
            "spike_wait_to_placed_s": round(released_s, 3),
            "victim_outcome": rec["outcome"],
            "mid_drain_kill_actor": deaf_killed_mid_drain,
            "wedged_pending_pgs": len(pending),
            "gate": f"placed within grace+6 s; outcome hard_kill; a "
                    f"victim actor was chaos-killed mid-drain; zero "
                    f"PENDING groups left",
            "pass": bool(
                released_s <= HARD_GRACE_S + 6.0
                and rec["outcome"] == "hard_kill"
                and deaf_killed_mid_drain is not None
                and not pending
            ),
        }
        print(json.dumps(entry))
        results.append(entry)
        remove_placement_group(spike2)
    finally:
        chaos.disable()
        chaos.clear()

    # -- probe 3: three tenants' SLO accounting in one run ---------------
    stop_traffic.set()
    stop_rl.set()
    for t in traffic:
        t.join(timeout=60)
    rl_thread.join(timeout=60)
    time.sleep(1.5)  # metrics flushers drain to the GCS
    snap = client._run(client._gcs_call("metrics_snapshot", {}))["metrics"]
    by_name = {m["name"]: m for m in snap}

    def tenants_of(metric):
        out = set()
        for tags, _ in (by_name.get(metric) or {}).get("series", []):
            t = dict(tuple(x) for x in tags).get("tenant")
            if t:
                out.add(t)
        return out

    req_tenants = tenants_of("serve_requests_total")
    burn_tenants = tenants_of("serve_slo_burn_rate")
    pre = by_name.get("preempt_total", {}).get("series", [])
    grace_hist = by_name.get("preempt_grace_seconds", {}).get("series", [])
    entry = {
        "metric": "three-tenant SLO accounting in one run",
        "chat_requests_ok": dict(chat_ok),
        "chat_shed": chat_shed[0],
        "lost_non_shed": len(chat_lost),
        "lost_samples": chat_lost[:5],
        "rl_steps_total": rl_steps[0],
        "rl_steps_during_spike": rl_during_spike,
        "request_series_tenants": sorted(req_tenants),
        "slo_burn_tenants": sorted(burn_tenants),
        "preempt_total_series": len(pre),
        "preempt_grace_observations": sum(
            s[1]["count"] for s in grace_hist
        ) if grace_hist else 0,
        "gate": "zero lost non-shed chat requests through both phases; "
                "request + SLO-burn series for train/serve/rl; RL made "
                "progress during the spike; preempt metrics populated",
        "pass": bool(
            not chat_lost
            and {"train", "serve", "rl"} <= req_tenants
            and {"train", "serve", "rl"} <= burn_tenants
            and rl_during_spike > 0
            and len(pre) >= 1
        ),
    }
    print(json.dumps(entry))
    results.append(entry)

    serve.shutdown()
    cluster.shutdown()

    summary = {
        "metric": "multi-tenant survival summary",
        "lost_requests_total": len(chat_lost),
        "gate": "lost_requests_total == 0",
        "pass": not chat_lost,
    }
    print(json.dumps(summary))
    results.append(summary)
    if not quick:
        with open("BENCH_MULTITENANT.json", "w") as f:
            json.dump(results, f, indent=1)
    failed = [r["metric"] for r in results if r.get("pass") is False]
    if failed:
        print(f"GATE FAILURES: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
