"""Topology-native collective benchmarks. Writes BENCH_COLLECTIVE.json.

Four probes, all deterministic on CPU loopback (the DCN "slow tier" is
manufactured with the chaos injections — a fixed per-send latency and a
bandwidth cap — so the measured regime is the modeled one, not whatever
the scheduler felt like):

  1. algorithm selection: the cost model on the 2-host x 4-chip
     topology must pick recursive doubling under the crossover size and
     sharded-hier above it (MIGRATION.md pins the crossover).
  2. rd vs ring latency: chaos-delayed n=4 ring, 1KB message —
     recursive doubling's log2(n) rounds must beat the ring's 2(n-1)
     serialized hops when the per-message alpha dominates.
  3. sharded-hier DCN bytes: 2 procs x 4 local devices, 64KB per
     device; total DCN wire bytes of the sharded two-tier exchange vs
     the flat ring in which all 8 devices are DCN members. GATE:
     ratio <= 1/n_local + 10%.
  4. int8 quantized wire: GATES: wire-byte reduction >= 3.5x, max
     relative error <= 1e-2, and error feedback closes the error over
     steps (20-step cumulative-mean error < single-shot error).

Gates are asserted here — a red gate makes the bench exit nonzero.

Run: python bench_collective.py [--quick]  (--quick: no artifact)
"""

from __future__ import annotations

import json
import sys
import threading
import time

import numpy as np

N_LOCAL = 4
HIER_ELEMS = 16 * 1024          # per-device fp32 elements for probe 3
QUANT_ELEMS = 64 * 1024         # per-rank fp32 elements for probe 4
RD_ELEMS = 256                  # 1KB message for the latency probe
SEND_DELAY_S = 0.004            # manufactured per-message DCN latency
EF_STEPS = 20


class _KV:
    """Dict-backed stand-in for the GCS KV (rendezvous only)."""

    def __init__(self):
        self.d, self.lock = {}, threading.Lock()

    def kv_put(self, k, v, ns=None):
        with self.lock:
            self.d[(ns, k)] = v

    def kv_get(self, k, ns=None):
        with self.lock:
            return self.d.get((ns, k))

    def kv_del(self, k, ns=None):
        with self.lock:
            self.d.pop((ns, k), None)


def _run(n, make, fn):
    groups, errs, out = [None] * n, [None] * n, [None] * n

    def mk(r):
        try:
            groups[r] = make(r)
        except Exception as e:  # noqa: BLE001
            errs[r] = e

    ts = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not any(errs), errs

    def work(r):
        try:
            out[r] = fn(groups[r], r)
        except Exception as e:  # noqa: BLE001
            errs[r] = e

    ts = [threading.Thread(target=work, args=(r,)) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for g in groups:
        g.destroy()
    assert not any(errs), errs
    return out, groups


def _dcn(n, fn, name, **kw):
    from ray_tpu.util.collective.dcn_group import DcnGroup

    kv = _KV()
    kw.setdefault("timeout", 30)
    kw.setdefault("op_timeout", 30)
    return _run(n, lambda r: DcnGroup(kv, n, r, name, **kw), fn)


def probe_selection(results):
    from ray_tpu.util.collective.topology import Topology

    topo = Topology.detect(2, n_local=N_LOCAL)
    cross = topo.crossover_nbytes()
    small = topo.select("allreduce", 1024)
    large = topo.select("allreduce", 64 << 20)
    entry = {
        "metric": "algorithm selection (2 hosts x 4 chips)",
        "selected_1KB": small,
        "selected_64MB": large,
        "crossover_KiB": cross // 1024,
    }
    assert small == "rd", f"gate: small-message algo {small} != rd"
    assert large == "hier", f"gate: large-message algo {large} != hier"
    print(json.dumps(entry))
    results.append(entry)


def probe_rd_vs_ring(results):
    """Fixed per-send chaos latency, tiny message: latency-bound regime."""
    from ray_tpu._private import chaos

    data = np.ones(RD_ELEMS, dtype=np.float32)

    def timed(algo):
        def fn(g, r):
            g.allreduce(data, algo=algo)  # warm up peer connections
            t0 = time.perf_counter()
            g.allreduce(data, algo=algo)
            return time.perf_counter() - t0

        chaos.delay_dcn_send(SEND_DELAY_S, count=10 ** 6)
        try:
            out, _ = _dcn(4, fn, f"lat_{algo}")
        finally:
            chaos.clear()
        return max(out)

    chaos.enable()
    try:
        ring_s = timed("ring")
        rd_s = timed("rd")
    finally:
        chaos.disable()
    entry = {
        "metric": "rd vs ring latency (chaos-delayed, n=4, 1KB)",
        "send_delay_ms": SEND_DELAY_S * 1e3,
        "ring_ms": round(ring_s * 1e3, 2),
        "rd_ms": round(rd_s * 1e3, 2),
        "speedup": round(ring_s / rd_s, 2),
    }
    assert rd_s < ring_s, (
        f"gate: rd ({rd_s * 1e3:.1f}ms) not faster than ring "
        f"({ring_s * 1e3:.1f}ms) at small nbytes"
    )
    print(json.dumps(entry))
    results.append(entry)


def probe_hier_bytes(results):
    from ray_tpu.util.collective.hier_group import HierarchicalGroup

    data = {
        r: [np.full(HIER_ELEMS, float(r * N_LOCAL + d), dtype=np.float32)
            for d in range(N_LOCAL)]
        for r in range(2)
    }
    kv = _KV()
    _, hg = _run(
        2,
        lambda r: HierarchicalGroup(kv, 2, r, "bh",
                                    num_local_devices=N_LOCAL, epoch=0),
        lambda g, r: g.allreduce(data[r], algo="hier"),
    )
    hier_total = sum(g.dcn.bytes_sent for g in hg)

    flat_in = [data[r][d] for r in range(2) for d in range(N_LOCAL)]
    _, fg = _dcn(8, lambda g, r: g.allreduce(flat_in[r], algo="ring"), "bf")
    flat_total = sum(g.bytes_sent for g in fg)

    ratio = hier_total / flat_total
    gate = 1 / N_LOCAL + 0.10
    entry = {
        "metric": "sharded-hier DCN bytes vs flat ring (2x4 devices)",
        "elems_per_device": HIER_ELEMS,
        "hier_dcn_bytes": hier_total,
        "flat_dcn_bytes": flat_total,
        "ratio": round(ratio, 4),
        "gate_max_ratio": round(gate, 3),
    }
    assert ratio <= gate, f"gate: hier/flat byte ratio {ratio:.3f} > {gate}"
    print(json.dumps(entry))
    results.append(entry)


def probe_quant(results):
    rng = np.random.default_rng(0)
    data = [rng.standard_normal(QUANT_ELEMS).astype(np.float32)
            for _ in range(2)]
    exact = data[0] + data[1]

    res_q, qg = _dcn(2, lambda g, r: g.allreduce(data[r], quant="int8"),
                     "bq")
    _, fg = _dcn(2, lambda g, r: g.allreduce(data[r]), "bqf")
    q_bytes = qg[0].last_op_info["bytes"]
    f_bytes = fg[0].last_op_info["bytes"]
    reduction = f_bytes / q_bytes
    rel_err = float(np.abs(res_q[0] - exact).max() / np.abs(exact).max())

    # error feedback: cumulative mean of repeated quantized sums must
    # converge toward the exact sum (EF-SGD telescoping)
    def ef_loop(g, r):
        outs = []
        for _ in range(EF_STEPS):
            outs.append(g.allreduce(data[r], quant="int8",
                                    error_feedback=True, ef_key="b"))
        return np.stack(outs)

    res_ef, _ = _dcn(2, ef_loop, "bef")
    single = float(np.abs(res_ef[0][0] - exact).max())
    mean_err = float(np.abs(res_ef[0].mean(axis=0) - exact).max())

    entry = {
        "metric": "int8 quantized DCN allreduce (n=2, 256KB fp32)",
        "fp32_wire_bytes": f_bytes,
        "int8_wire_bytes": q_bytes,
        "wire_reduction": round(reduction, 2),
        "max_rel_error": round(rel_err, 6),
        "ef_single_shot_error": round(single, 6),
        "ef_mean_error_20_steps": round(mean_err, 6),
    }
    assert reduction >= 3.5, f"gate: wire reduction {reduction:.2f} < 3.5"
    assert rel_err <= 1e-2, f"gate: max rel error {rel_err:.4f} > 1e-2"
    assert mean_err < single, (
        f"gate: EF mean error {mean_err} not below single-shot {single}"
    )
    print(json.dumps(entry))
    results.append(entry)


def main():
    quick = "--quick" in sys.argv
    results = []
    probe_selection(results)
    probe_rd_vs_ring(results)
    probe_hier_bytes(results)
    probe_quant(results)
    if not quick:
        with open("BENCH_COLLECTIVE.json", "w") as f:
            json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
