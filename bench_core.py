"""Core runtime microbenchmarks — the ray_perf suite for this runtime.

Mirrors the reference's release/microbenchmark harness
(python/ray/_private/ray_perf.py:129-250): tasks/s sync and async, actor
calls/s 1:1 and async, put/get throughput for small and large objects.
Prints one JSON line per benchmark and writes BENCH_CORE.json.

Run: python bench_core.py [--quick]

## Throughput analysis (round 4)

Measured on this image's single-core host (results in BENCH_CORE.json,
median of 2 runs): ~2.2k trivial tasks/s sync, ~14.5k tasks/s pipelined,
~2.2k/14.3k actor calls/s sync/async, ~21k small puts/s, actor
register+ready+call ~95/s, ~5 GB/s large-object put+get (shared-memory
zero-copy). Round-4 changes that moved these numbers (r3: 3.4k async
tasks/s, 1.6k async actor calls/s, 3.6k puts/s, 42.5 actors/s):
  * Batched direct transport (worker.py _submit_direct_group -> worker
    h_run_tasks_batch): a burst of same-shape tasks rides one RPC frame
    and ONE worker-side executor hop per chunk of 32, spread across the
    lease pool by outstanding count.
  * Actor-call batch frames (_actor_call_group -> h_actor_call_batch)
    with contiguous seq runs executing in one executor hop.
  * Async batched primary-copy registration: put() returns at store
    seal; object_created notifications coalesce per loop tick into one
    raylet frame, and the raylet registers locations with the GCS in one
    batched frame (the reference's async plasma-notification socket
    role).
  * Actor-worker recycling (raylet _try_recycle_actor_worker -> worker
    h_release_actor): a cleanly-killed idle actor's worker returns to
    the pool; steady-state create/call/kill cycles fork nothing. Plus a
    demand-triggered min-idle warm pool (debounced replenish) and a
    zygote prewarm (first-use executor/event-loop machinery exercised
    pre-fork: ~8ms off every worker boot).
Sync (one-at-a-time) round trips stay ~2k/s: on this 1-core host each
call pays context switches through driver/worker processes timesharing
the core; the reference's C++ CoreWorker path measures its 10-20k/s on
multi-core hosts where the peers run in parallel.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import ray_tpu as rt


def timeit(name, fn, multiplier=1, duration=2.0, results=None):
    """Run fn repeatedly for ~duration seconds, report ops/s."""
    # Warm twice: the first call may spawn workers / settle the pool.
    fn()
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < duration:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    entry = {"benchmark": name, "ops_per_s": round(rate, 1)}
    print(json.dumps(entry), flush=True)
    if results is not None:
        results.append(entry)
    return rate


def main():
    quick = "--quick" in sys.argv
    duration = 1.0 if quick else 3.0
    rt.init(num_cpus=4, object_store_memory=1024 * 1024 * 1024)
    results = []

    @rt.remote
    def small_value():
        return b"ok"

    @rt.remote
    class Actor:
        def small_value(self):
            return b"ok"

    # -- tasks ----------------------------------------------------------
    timeit(
        "single client tasks sync",
        lambda: rt.get(small_value.remote()),
        duration=duration, results=results,
    )

    n = 100
    timeit(
        "single client tasks async",
        lambda: rt.get([small_value.remote() for _ in range(n)]),
        multiplier=n, duration=duration, results=results,
    )

    # -- actor calls ----------------------------------------------------
    a = Actor.remote()
    rt.get(a.small_value.remote())
    timeit(
        "1:1 actor calls sync",
        lambda: rt.get(a.small_value.remote()),
        duration=duration, results=results,
    )
    timeit(
        "1:1 actor calls async",
        lambda: rt.get([a.small_value.remote() for _ in range(n)]),
        multiplier=n, duration=duration, results=results,
    )

    # -- objects --------------------------------------------------------
    small = b"x" * 1024
    timeit(
        "put small (1KB) objects",
        lambda: rt.put(small),
        duration=duration, results=results,
    )

    big = np.zeros(128 * 1024 * 1024 // 8, dtype=np.float64)  # 128 MB
    gb = big.nbytes / 1e9

    def put_get_big():
        ref = rt.put(big)
        out = rt.get(ref)
        assert out.nbytes == big.nbytes
        del out, ref

    rate = timeit(
        "put+get 128MB (roundtrips)",
        put_get_big,
        duration=duration, results=results,
    )
    results.append(
        {"benchmark": "put+get throughput", "gb_per_s": round(rate * gb, 2)}
    )
    print(json.dumps(results[-1]), flush=True)

    # -- GCS control-plane ops (VERDICT r2 item 6) ----------------------
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client()
    counter = iter(range(10_000_000))

    def kv_put():
        client.kv_put(f"bench-key-{next(counter)}".encode(), b"v" * 64)

    timeit("gcs kv puts", kv_put, duration=duration, results=results)

    def register_actors():
        batch = [
            Actor.options(num_cpus=0.0001).remote() for _ in range(20)
        ]
        rt.get([x.small_value.remote() for x in batch], timeout=300)
        for x in batch:
            rt.kill(x)

    timeit(
        "actor register+ready+call (batch of 20)",
        register_actors,
        multiplier=20, duration=duration, results=results,
    )

    # Data -> device feed: zero-copy batching out of the shm store into
    # a jitted consumer (SURVEY §7 "Plasma<->HBM boundary"; batches are
    # views over the store until the single host->HBM device_put).
    import jax

    import ray_tpu.data as rtd

    feed_ds = rtd.from_numpy(
        {"x": np.arange(256 * 128, dtype=np.float32).reshape(256 * 128)},
        parallelism=4,
    )

    @jax.jit
    def _consume(batch):
        return batch["x"].sum()

    def feed_batches():
        n = 0
        for batch in feed_ds.iter_jax_batches(batch_size=1024):
            _consume(batch).block_until_ready()  # rtlint: disable=RT001 — the probe measures the consumer's per-batch sync on purpose
            n += 1
        return n

    n_batches = feed_batches()  # warm compile outside the timing window
    timeit(
        f"data->device feed ({n_batches} x 1024-row batches, jitted sum)",
        feed_batches,
        multiplier=n_batches, duration=duration, results=results,
    )

    if not quick:
        # --quick is a smoke run with 1s windows on a possibly-loaded box;
        # only full runs overwrite the committed artifact.
        with open("BENCH_CORE.json", "w") as f:
            json.dump(results, f, indent=1)
    rt.shutdown()


if __name__ == "__main__":
    main()
