"""Core runtime microbenchmarks — the ray_perf suite for this runtime.

Mirrors the reference's release/microbenchmark harness
(python/ray/_private/ray_perf.py:129-250): tasks/s sync and async, actor
calls/s 1:1 and async, put/get throughput for small and large objects.
Prints one JSON line per benchmark and writes BENCH_CORE.json.

Run: python bench_core.py [--quick]

## Throughput ceiling analysis (VERDICT r1 item 4)

Measured on this image's single-core host (results in BENCH_CORE.json):
~1.4k trivial tasks/s sync, ~1.9k actor calls/s async, ~7 GB/s large-object
put+get (shared-memory zero-copy; owner-driven ref GC keeps the store from
filling, which is what took this from 0.16 GB/s in round 1).

Why not 10k tasks/s here: the reference's 10-20k/s/core comes from a C++
CoreWorker whose per-task submit cost is ~30-60µs of C++ on an
uncontended core. This runtime's per-task path is pure Python asyncio:
driver serialize + frame (~100µs), raylet dispatch (~150µs), worker
execute + reply (~200µs), driver complete (~100µs) — ~0.6ms of Python
per task spread across 3 processes that SHARE ONE physical core in this
environment, so the end-to-end ceiling is ~1.5-2k/s. The two classic
architectural fixes are already in place upstream of the interpreter
cost: batched dispatch waves (the event-driven dispatch loop drains the
whole queue per wake-up — no per-task sleeps) and no per-task worker
spawning (pool reuse + capacity-capped prestart). The remaining 10x is
interpreter cost, reachable only by moving the hot loop out of Python
(the reference's Cython/_raylet.pyx role) — a deliberate non-goal this
round; on a TPU pod host (dozens of real cores) the same code measures
several-fold higher since driver/raylet/worker stop timesharing one core.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import ray_tpu as rt


def timeit(name, fn, multiplier=1, duration=2.0, results=None):
    """Run fn repeatedly for ~duration seconds, report ops/s."""
    # Warm twice: the first call may spawn workers / settle the pool.
    fn()
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < duration:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    entry = {"benchmark": name, "ops_per_s": round(rate, 1)}
    print(json.dumps(entry), flush=True)
    if results is not None:
        results.append(entry)
    return rate


def main():
    quick = "--quick" in sys.argv
    duration = 1.0 if quick else 3.0
    rt.init(num_cpus=4, object_store_memory=1024 * 1024 * 1024)
    results = []

    @rt.remote
    def small_value():
        return b"ok"

    @rt.remote
    class Actor:
        def small_value(self):
            return b"ok"

    # -- tasks ----------------------------------------------------------
    timeit(
        "single client tasks sync",
        lambda: rt.get(small_value.remote()),
        duration=duration, results=results,
    )

    n = 100
    timeit(
        "single client tasks async",
        lambda: rt.get([small_value.remote() for _ in range(n)]),
        multiplier=n, duration=duration, results=results,
    )

    # -- actor calls ----------------------------------------------------
    a = Actor.remote()
    rt.get(a.small_value.remote())
    timeit(
        "1:1 actor calls sync",
        lambda: rt.get(a.small_value.remote()),
        duration=duration, results=results,
    )
    timeit(
        "1:1 actor calls async",
        lambda: rt.get([a.small_value.remote() for _ in range(n)]),
        multiplier=n, duration=duration, results=results,
    )

    # -- objects --------------------------------------------------------
    small = b"x" * 1024
    timeit(
        "put small (1KB) objects",
        lambda: rt.put(small),
        duration=duration, results=results,
    )

    big = np.zeros(128 * 1024 * 1024 // 8, dtype=np.float64)  # 128 MB
    gb = big.nbytes / 1e9

    def put_get_big():
        ref = rt.put(big)
        out = rt.get(ref)
        assert out.nbytes == big.nbytes
        del out, ref

    rate = timeit(
        "put+get 128MB (roundtrips)",
        put_get_big,
        duration=duration, results=results,
    )
    results.append(
        {"benchmark": "put+get throughput", "gb_per_s": round(rate * gb, 2)}
    )
    print(json.dumps(results[-1]), flush=True)

    with open("BENCH_CORE.json", "w") as f:
        json.dump(results, f, indent=1)
    rt.shutdown()


if __name__ == "__main__":
    main()
