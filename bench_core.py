"""Core runtime microbenchmarks — the ray_perf suite for this runtime.

Mirrors the reference's release/microbenchmark harness
(python/ray/_private/ray_perf.py:129-250): tasks/s sync and async, actor
calls/s 1:1 and async, put/get throughput for small and large objects.
Prints one JSON line per benchmark and writes BENCH_CORE.json.

Run: python bench_core.py [--quick]

## Throughput analysis (round 3)

Measured on this image's single-core host (results in BENCH_CORE.json):
~1.8k trivial tasks/s sync, 3.5-6.5k tasks/s pipelined (async; this
shared host's load swings runs), ~1.5k/2k actor calls/s sync/async,
~8-9 GB/s large-object put+get (shared-memory zero-copy). Round-3
changes that moved these numbers:
  * Direct task transport (worker.py _submit_direct + raylet
    h_lease_worker): the owner leases workers once per scheduling class
    and streams task specs straight to them — the raylet is off the
    per-task path entirely (reference: direct_task_transport.cc:197
    OnWorkerIdle lease reuse). Pipelined task throughput went 1.4k/s ->
    ~6k/s.
  * Submit burst batching (worker.py _drain_submits): a burst of
    .remote() calls crosses the thread->loop boundary once, and
    protocol.FrameSender coalesces same-tick frames into one socket
    write (7 syscalls/task -> ~2).
  * Function-key identity cache (function_manager.py): no per-submit
    cloudpickle of the function.
The remaining gap to the reference's 10-20k/s/core is interpreter cost
in the per-task execute path (the reference runs it in C++ CoreWorker,
core_worker.cc:1935); on a TPU pod host with real cores the processes
stop timesharing one core and the same code measures several-fold
higher. Scale probes (bench_scale.py): 10k queued tasks drain in ~3-8s
(O(classes) per-wakeup dispatch + direct transport; was 97.8s), 200
actors create+call in ~4.6s (zygote fork server, _private/zygote.py),
and a 1GB cross-node broadcast moves in ~4s under pull/push flow
control.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import ray_tpu as rt


def timeit(name, fn, multiplier=1, duration=2.0, results=None):
    """Run fn repeatedly for ~duration seconds, report ops/s."""
    # Warm twice: the first call may spawn workers / settle the pool.
    fn()
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < duration:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    entry = {"benchmark": name, "ops_per_s": round(rate, 1)}
    print(json.dumps(entry), flush=True)
    if results is not None:
        results.append(entry)
    return rate


def main():
    quick = "--quick" in sys.argv
    duration = 1.0 if quick else 3.0
    rt.init(num_cpus=4, object_store_memory=1024 * 1024 * 1024)
    results = []

    @rt.remote
    def small_value():
        return b"ok"

    @rt.remote
    class Actor:
        def small_value(self):
            return b"ok"

    # -- tasks ----------------------------------------------------------
    timeit(
        "single client tasks sync",
        lambda: rt.get(small_value.remote()),
        duration=duration, results=results,
    )

    n = 100
    timeit(
        "single client tasks async",
        lambda: rt.get([small_value.remote() for _ in range(n)]),
        multiplier=n, duration=duration, results=results,
    )

    # -- actor calls ----------------------------------------------------
    a = Actor.remote()
    rt.get(a.small_value.remote())
    timeit(
        "1:1 actor calls sync",
        lambda: rt.get(a.small_value.remote()),
        duration=duration, results=results,
    )
    timeit(
        "1:1 actor calls async",
        lambda: rt.get([a.small_value.remote() for _ in range(n)]),
        multiplier=n, duration=duration, results=results,
    )

    # -- objects --------------------------------------------------------
    small = b"x" * 1024
    timeit(
        "put small (1KB) objects",
        lambda: rt.put(small),
        duration=duration, results=results,
    )

    big = np.zeros(128 * 1024 * 1024 // 8, dtype=np.float64)  # 128 MB
    gb = big.nbytes / 1e9

    def put_get_big():
        ref = rt.put(big)
        out = rt.get(ref)
        assert out.nbytes == big.nbytes
        del out, ref

    rate = timeit(
        "put+get 128MB (roundtrips)",
        put_get_big,
        duration=duration, results=results,
    )
    results.append(
        {"benchmark": "put+get throughput", "gb_per_s": round(rate * gb, 2)}
    )
    print(json.dumps(results[-1]), flush=True)

    # -- GCS control-plane ops (VERDICT r2 item 6) ----------------------
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client()
    counter = iter(range(10_000_000))

    def kv_put():
        client.kv_put(f"bench-key-{next(counter)}".encode(), b"v" * 64)

    timeit("gcs kv puts", kv_put, duration=duration, results=results)

    def register_actors():
        batch = [
            Actor.options(num_cpus=0.0001).remote() for _ in range(20)
        ]
        rt.get([x.small_value.remote() for x in batch], timeout=300)
        for x in batch:
            rt.kill(x)

    timeit(
        "actor register+ready+call (batch of 20)",
        register_actors,
        multiplier=20, duration=duration, results=results,
    )

    with open("BENCH_CORE.json", "w") as f:
        json.dump(results, f, indent=1)
    rt.shutdown()


if __name__ == "__main__":
    main()
